"""HTTP-edge admission control: priority classes, bounded queues, shedding.

The actuated end of the planner's saturation decisions. Requests carry a
priority class in the ``X-Priority`` header (``high`` / ``normal`` /
``low``, or the numeric level); the controller admits up to ``limit``
concurrently, queues the overflow per priority class (bounded depth,
queue-wait deadline), and grants freed slots highest-priority-first.
When the planner signals saturation (``set_shed_level``), the lowest
classes are rejected at the door with 429 + ``Retry-After`` — and any of
their requests already queued are flushed with the same rejection, so a
spike degrades queued TTFT for the best traffic instead of toppling the
engines for all of it.

Every decision is observable: ``dynamo_planner_*`` instruments on the
controller's registry (attached into the HTTP service's scrape) and
flight-recorder events (``planner.shed`` / ``planner.admit_timeout``)
so `/debug/flight` can reconstruct exactly which requests were turned
away and why.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import math
import time
from typing import Callable, Deque, Dict, Optional

from ..telemetry.flight import FlightRecorder, flight_recorder
from ..telemetry.registry import MetricsRegistry

# index IS the priority level: 0 sheds first, the last class never sheds
PRIORITY_CLASSES = ("low", "normal", "high")
DEFAULT_PRIORITY = PRIORITY_CLASSES.index("normal")
PRIORITY_HEADER = "X-Priority"


def parse_priority(value: Optional[str]) -> int:
    """Header value → priority level. Unknown/absent values map to
    ``normal`` — a malformed header must degrade to default service,
    not to an error or (worse) to highest priority."""
    if not value:
        return DEFAULT_PRIORITY
    v = value.strip().lower()
    if v in PRIORITY_CLASSES:
        return PRIORITY_CLASSES.index(v)
    try:
        level = int(v)
    except ValueError:
        return DEFAULT_PRIORITY
    if 0 <= level < len(PRIORITY_CLASSES):
        return level
    return DEFAULT_PRIORITY


class AdmissionRejected(Exception):
    """Request turned away at the edge; carries the Retry-After hint."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 outcome: str = "shed"):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.outcome = outcome  # "shed" | "queue_full" | "timeout" | "draining"

    @property
    def retry_after_header(self) -> str:
        return str(max(1, math.ceil(self.retry_after_s)))


@dataclasses.dataclass
class AdmissionConfig:
    limit: int = 0               # concurrently admitted requests; 0 = unbounded
    queue_depth: int = 64        # per-priority-class queue bound
    queue_timeout_s: float = 10.0  # queue-wait deadline
    retry_after_s: float = 1.0   # hint on shed/queue-full rejections


class _Waiter:
    __slots__ = ("fut", "priority", "enqueued_t", "granted", "abandoned")

    def __init__(self, fut: asyncio.Future, priority: int, enqueued_t: float):
        self.fut = fut
        self.priority = priority
        self.enqueued_t = enqueued_t
        self.granted = False
        self.abandoned = False


class AdmissionController:
    """Priority-aware concurrency gate for the HTTP edge.

    Single-loop discipline: all state mutation happens on the event loop
    (no locks); the grant path runs synchronously inside ``release`` /
    ``set_limit`` / ``set_shed_level`` so admitted-vs-abandoned races
    reduce to flag checks within one loop iteration.
    """

    def __init__(
        self,
        config: Optional[AdmissionConfig] = None,
        registry: Optional[MetricsRegistry] = None,
        flight: Optional[FlightRecorder] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config or AdmissionConfig()
        self.limit = self.config.limit
        self.shed_level = 0
        self.clock = clock
        self.flight = flight if flight is not None else flight_recorder()
        self._inflight = 0
        self._queues: Dict[int, Deque[_Waiter]] = {
            level: collections.deque()
            for level in range(len(PRIORITY_CLASSES))
        }
        self.shed_total = 0  # lifetime rejections, planner signal
        # recovery drain (recovery/controller.py): while True EVERY class
        # is rejected at the door — a draining worker takes nothing new,
        # regardless of shed level or free slots
        self.draining = False

        self.registry = registry or MetricsRegistry()
        self._admissions = self.registry.counter(
            "dynamo_planner_admissions_total",
            "Admission decisions by priority= class and outcome="
            "admitted|shed|queue_full|timeout|draining",
        )
        self._queue_wait = self.registry.histogram(
            "dynamo_planner_queue_wait_seconds",
            "Admission-queue wait of ADMITTED requests, by priority=",
        )
        self.registry.callback_gauge(
            "dynamo_planner_admission_queue_depth_requests",
            "Requests waiting in the admission queue, by priority=",
            # dynrace: domain(executor)
            lambda: [
                ({"priority": PRIORITY_CLASSES[level]}, self.queue_depth(level))
                for level in self._queues
            ],
        )
        self.registry.callback_gauge(
            "dynamo_planner_inflight_requests",
            "Requests admitted and not yet released",
            # dynrace: domain(executor)
            lambda: self._inflight,
        )
        self.registry.callback_gauge(
            "dynamo_planner_admission_limit_requests",
            "Current admission concurrency limit (0 = unbounded)",
            # dynrace: domain(executor)
            lambda: self.limit,
        )
        self.registry.callback_gauge(
            "dynamo_planner_shedding_info",
            "1 when the priority= class is currently being shed",
            # dynrace: domain(executor)
            lambda: [
                ({"priority": PRIORITY_CLASSES[level]},
                 1 if level < self.shed_level else 0)
                for level in range(len(PRIORITY_CLASSES))
            ],
        )

    # ---------- introspection ----------

    def queue_depth(self, level: Optional[int] = None) -> int:
        if level is not None:
            return sum(1 for w in self._queues[level] if not w.abandoned)
        return sum(self.queue_depth(lv) for lv in self._queues)

    @property
    def inflight(self) -> int:
        return self._inflight

    def snapshot(self) -> Dict[str, float]:
        """Planner signal source (names from planner/policy.py)."""
        limit = self.limit
        return {
            "admission.queue_depth": float(self.queue_depth()),
            "admission.inflight_ratio": (
                self._inflight / limit if limit > 0 else 0.0
            ),
            "admission.shed_total": float(self.shed_total),
        }

    # ---------- planner-facing knobs ----------

    def set_limit(self, limit: int) -> None:
        self.limit = max(0, int(limit))
        self._grant_free_slots()

    def set_shed_level(self, level: int) -> None:
        """Shed classes below ``level``; flush their queued waiters so a
        request already waiting doesn't burn its deadline just to be
        turned away anyway."""
        level = max(0, min(int(level), len(PRIORITY_CLASSES) - 1))
        self.shed_level = level
        for class_level in range(level):
            queue = self._queues[class_level]
            while queue:
                w = queue.popleft()
                if w.abandoned or w.fut.done():
                    continue
                w.fut.set_exception(self._rejection(class_level, "shed"))
        self._grant_free_slots()

    def set_draining(self, draining: bool = True) -> None:
        """Drain-aware admission: reject every class while the engine
        behind this edge drains (recovery ladder / rolling update), and
        flush already-queued waiters — their wait can only end in a
        migration or a restart, never an admission."""
        self.draining = draining
        if not draining:
            self._grant_free_slots()
            return
        for queue in self._queues.values():
            while queue:
                w = queue.popleft()
                if w.abandoned or w.fut.done():
                    continue
                w.fut.set_exception(self._rejection(w.priority, "draining"))

    # ---------- request path ----------

    async def acquire(self, priority: int, request_id: str = "") -> None:
        """Admit, queue, or reject one request. Raises
        :class:`AdmissionRejected` on shed / queue-full / deadline."""
        priority = max(0, min(int(priority), len(PRIORITY_CLASSES) - 1))
        cls = PRIORITY_CLASSES[priority]
        if self.draining:
            self._count_rejection(priority, "draining", request_id)
            raise self._rejection(priority, "draining")
        if priority < self.shed_level:
            self._count_rejection(priority, "shed", request_id)
            raise self._rejection(priority, "shed")
        if self.limit <= 0 or self._inflight < self.limit:
            self._inflight += 1
            self._admissions.inc(priority=cls, outcome="admitted")
            self._queue_wait.observe(0.0, priority=cls)
            return
        queue = self._queues[priority]
        if self.queue_depth(priority) >= self.config.queue_depth:
            self._count_rejection(priority, "queue_full", request_id)
            raise self._rejection(priority, "queue_full")
        loop = asyncio.get_running_loop()
        w = _Waiter(loop.create_future(), priority, self.clock())
        queue.append(w)
        try:
            # shield: a deadline must not cancel a grant that landed in
            # the same loop iteration — the granted flag disambiguates
            await asyncio.wait_for(
                asyncio.shield(w.fut), self.config.queue_timeout_s)
        except asyncio.TimeoutError:
            if w.granted:
                pass  # slot granted as the deadline fired: admitted
            else:
                self._discard(w)
                self._count_rejection(priority, "timeout", request_id)
                self.flight.record(
                    "planner.admit_timeout", request_id=request_id or None,
                    priority=cls,
                    waited_s=round(self.clock() - w.enqueued_t, 4),
                )
                raise self._rejection(priority, "timeout")
        except asyncio.CancelledError:
            # client went away while queued
            if not w.granted:
                self._discard(w)
                raise
            # granted and cancelled in the same iteration: give the slot
            # back before propagating
            self._inflight -= 1
            self._grant_free_slots()
            raise
        except AdmissionRejected as e:
            # set_shed_level / set_draining flushed this waiter mid-queue
            self._count_rejection(
                priority, getattr(e, "outcome", None) or "shed", request_id
            )
            raise
        self._admissions.inc(priority=cls, outcome="admitted")
        self._queue_wait.observe(
            self.clock() - w.enqueued_t, priority=cls)

    def release(self) -> None:
        """One admitted request finished; hand its slot to the best
        queued waiter."""
        self._inflight = max(0, self._inflight - 1)
        self._grant_free_slots()

    # ---------- internals ----------

    def _rejection(self, priority: int, outcome: str) -> AdmissionRejected:
        cls = PRIORITY_CLASSES[priority]
        if outcome == "shed":
            msg = (f"service saturated; priority class {cls!r} is being "
                   f"shed — retry later")
        elif outcome == "draining":
            msg = ("worker is draining (recovery or rolling update) — "
                   "retry against the pool")
        elif outcome == "queue_full":
            msg = f"admission queue full for priority class {cls!r}"
        else:
            msg = (f"request exceeded the admission queue-wait deadline "
                   f"({self.config.queue_timeout_s:.0f}s)")
        return AdmissionRejected(
            msg, retry_after_s=self.config.retry_after_s, outcome=outcome)

    def _count_rejection(self, priority: int, outcome: str,
                         request_id: str) -> None:
        cls = PRIORITY_CLASSES[priority]
        self.shed_total += 1
        self._admissions.inc(priority=cls, outcome=outcome)
        if outcome != "timeout":  # timeout records its own richer event
            self.flight.record(
                "planner.shed", request_id=request_id or None,
                priority=cls, outcome=outcome, shed_level=self.shed_level,
            )

    def _discard(self, w: _Waiter) -> None:
        """Remove a timed-out/cancelled waiter from its queue NOW — the
        abandoned flag alone would leave the object in the deque until a
        grant walks past it, which under a sustained retry storm (every
        client re-queueing each deadline) grows the deque without bound."""
        w.abandoned = True
        try:
            self._queues[w.priority].remove(w)
        except ValueError:
            pass  # already popped by a racing grant/flush

    def _pop_highest(self) -> Optional[_Waiter]:
        for level in range(len(PRIORITY_CLASSES) - 1, -1, -1):
            queue = self._queues[level]
            while queue:
                w = queue.popleft()
                if w.abandoned or w.fut.done():
                    continue
                return w
        return None

    def _grant_free_slots(self) -> None:
        while self.limit <= 0 or self._inflight < self.limit:
            w = self._pop_highest()
            if w is None:
                return
            self._inflight += 1
            w.granted = True
            w.fut.set_result(None)
