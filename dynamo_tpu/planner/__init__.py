"""Closed-loop SLA planner: telemetry in, scaling/admission actions out.

The subsystem between observation and actuation (reference deployment
plane, PAPER.md §1 layer 9): a rolling-window :class:`SignalStore`
feeds a deterministic :class:`SlaPolicy` whose typed actions — scale a
worker pool, rebalance the disagg split, tighten admission — are
applied by pluggable actuators (K8s Reconciler patch, api-store record
update, in-process router/admission knobs). The HTTP edge's
:class:`AdmissionController` is the load-shedding end of the loop.
"""

from .admission import (
    PRIORITY_CLASSES,
    PRIORITY_HEADER,
    AdmissionConfig,
    AdmissionController,
    AdmissionRejected,
    parse_priority,
)
from .actuation import (
    KubeActuator,
    LocalActuator,
    StoreScaleActuator,
    scale_cr_service,
)
from .planner import (
    Planner,
    PlannerConfig,
    aggregator_source,
    engine_metrics_source,
    slo_source,
)
from .policy import (
    Action,
    AdmissionAction,
    PolicyConfig,
    RebalanceAction,
    ScaleAction,
    SlaPolicy,
)
from .signals import SignalStore

__all__ = [
    "Action",
    "AdmissionAction",
    "AdmissionConfig",
    "AdmissionController",
    "AdmissionRejected",
    "KubeActuator",
    "LocalActuator",
    "Planner",
    "PlannerConfig",
    "PolicyConfig",
    "PRIORITY_CLASSES",
    "PRIORITY_HEADER",
    "RebalanceAction",
    "ScaleAction",
    "SignalStore",
    "SlaPolicy",
    "StoreScaleActuator",
    "aggregator_source",
    "engine_metrics_source",
    "parse_priority",
    "scale_cr_service",
    "slo_source",
]
