"""Actuators: typed planner actions → cluster / router / edge changes.

Three actuation paths, all behind one ``apply(action) -> bool`` protocol
(False = "not mine", so the planner just offers each action down its
actuator list):

- :class:`KubeActuator` — patches per-role replica counts into the CR
  spec and drives the existing deploy ``Reconciler``, so the SAME diff/
  apply/prune machinery serves the planner as serves the operator:
  ``InMemoryKube`` tests the loop end-to-end, ``KubectlClient`` /
  ``KubeApiClient`` run it for real. Reconcile work (kubectl subprocess,
  REST) rides an executor — the planner loop must never block.
- :class:`StoreScaleActuator` — writes the replica change into the
  api-store record instead; the operator sourcing CRs from the store
  (``--api-store-url``) applies it on its next pass. This is the
  planner-as-its-own-pod path where the planner has no cluster creds.
- :class:`LocalActuator` — in-process knobs: the disagg router's
  local/remote threshold (optionally fanned out to every live router
  through the discovery plane via ``DisaggRouter.publish_config``) and
  the admission controller's shed level / limit.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Mapping, Optional

from ..deploy.operator import Reconciler
from .admission import AdmissionController
from .policy import Action, AdmissionAction, RebalanceAction, ScaleAction

logger = logging.getLogger(__name__)


def scale_cr_service(cr: dict, service: str, replicas: int) -> dict:
    """Set one service's replica count in a CR spec (in place). The
    service entry is created if the CR relied on render-time defaults."""
    services = cr["spec"].setdefault("services", {})
    spec = services.setdefault(service, {"role": service})
    spec["replicas"] = int(replicas)
    return cr


class KubeActuator:
    """ScaleActions → CR replica patches through the deploy Reconciler."""

    def __init__(
        self,
        reconciler: Reconciler,
        cr: dict,
        role_services: Optional[Mapping[str, str]] = None,
    ):
        self.reconciler = reconciler
        self.cr = cr
        # role → service name; by default resolved from the CR's own
        # service specs (a service's role defaults to its name)
        self._role_services = dict(role_services or {})

    def _service_for_role(self, role: str) -> Optional[str]:
        if role in self._role_services:
            return self._role_services[role]
        for service, spec in (self.cr["spec"].get("services") or {}).items():
            if spec.get("role", service) == role:
                return service
        return None

    def replicas(self) -> Dict[str, int]:
        """role → current replica count, for the policy's targets."""
        out: Dict[str, int] = {}
        for service, spec in (self.cr["spec"].get("services") or {}).items():
            out[spec.get("role", service)] = int(spec.get("replicas", 1))
        return out

    async def apply(self, action: Action) -> bool:
        if not isinstance(action, ScaleAction):
            return False
        service = self._service_for_role(action.role)
        if service is None:
            logger.warning("no service for role %r in CR %s — scale skipped",
                           action.role, self.cr["metadata"]["name"])
            return False
        scale_cr_service(self.cr, service, action.target_replicas)
        # reconcile off-loop: the kubectl/REST client blocks
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, self.reconciler.reconcile, self.cr)
        return True


class StoreScaleActuator:
    """ScaleActions → api-store record updates (operator applies them)."""

    def __init__(self, store_client, deployment: str,
                 role_services: Optional[Mapping[str, str]] = None):
        self.store = store_client  # deploy.store_source.ApiStoreClient (sync)
        self.deployment = deployment
        self._role_services = dict(role_services or {})

    def _patch(self, role: str, target: int) -> Optional[Dict[str, int]]:
        rec = self.store.get(self.deployment)
        if rec is None:
            logger.warning("deployment %r not in api-store — scale skipped",
                           self.deployment)
            return None
        spec = rec["spec"]
        services = spec.setdefault("services", {})
        service = self._role_services.get(role)
        if service is None:
            for name, sspec in services.items():
                if sspec.get("role", name) == role:
                    service = name
                    break
        if service is None:
            service = role
        services.setdefault(service, {"role": role})["replicas"] = int(target)
        self.store.update(self.deployment, spec)
        return {
            sspec.get("role", name): int(sspec.get("replicas", 1))
            for name, sspec in services.items()
        }

    async def replicas(self) -> Dict[str, int]:
        loop = asyncio.get_running_loop()
        try:
            rec = await loop.run_in_executor(
                None, self.store.get, self.deployment)
        except Exception:
            logger.warning("api-store unreachable for replica lookup",
                           exc_info=True)
            return {}
        if rec is None:
            return {}
        return {
            spec.get("role", name): int(spec.get("replicas", 1))
            for name, spec in (rec["spec"].get("services") or {}).items()
        }

    async def apply(self, action: Action) -> bool:
        if not isinstance(action, ScaleAction):
            return False
        loop = asyncio.get_running_loop()
        patched = await loop.run_in_executor(
            None, self._patch, action.role, action.target_replicas)
        return patched is not None


class LocalActuator:
    """In-process actuation: disagg router config + admission knobs."""

    def __init__(
        self,
        disagg_router=None,          # disagg.router.DisaggRouter
        admission: Optional[AdmissionController] = None,
        discovery=None,              # publish config to every live router
        namespace: str = "public",
        model_name: Optional[str] = None,
    ):
        self.disagg_router = disagg_router
        self.admission = admission
        self.discovery = discovery
        self.namespace = namespace
        self.model_name = model_name

    async def apply(self, action: Action) -> bool:
        if isinstance(action, RebalanceAction):
            applied = False
            if self.disagg_router is not None:
                self.disagg_router.max_local_prefill_length = (
                    action.max_local_prefill_length)
                self.disagg_router.max_prefill_queue_size = (
                    action.max_prefill_queue_size)
                applied = True
            if self.discovery is not None:
                # the watched-config path: every live router (decode
                # workers included) applies the new threshold
                from ..disagg.router import DisaggRouter

                await DisaggRouter.publish_config(
                    self.discovery, self.namespace, self.model_name,
                    action.max_local_prefill_length,
                    action.max_prefill_queue_size,
                )
                applied = True
            return applied
        if isinstance(action, AdmissionAction):
            if self.admission is None:
                return False
            self.admission.set_shed_level(action.shed_level)
            if action.limit is not None:
                self.admission.set_limit(action.limit)
            return True
        return False
