"""The planner loop: observe → decide → actuate, on a fixed cadence.

The control plane between the telemetry the repo already gathers and
the knobs it already has (ROADMAP item 1; reference deployment plane,
PAPER.md §1 layer 9). Each cycle:

1. **observe** — poll every registered signal source (plain callables
   returning ``{signal_name: value}``; the KvMetricsAggregator snapshot,
   an AdmissionController, an engine's ForwardPassMetrics dict, a
   scripted test feed) into the rolling :class:`SignalStore`.
2. **decide** — run :class:`~dynamo_tpu.planner.policy.SlaPolicy`
   against the store plus the current role→replica map.
3. **actuate** — offer each emitted action down the actuator list
   (planner/actuation.py); the first actuator that claims it wins.

Every decision is recorded: ``dynamo_planner_actions_total`` /
``dynamo_planner_replica_target_replicas`` on the planner's registry
and a ``planner.action`` flight-recorder event, so `/debug/flight`
shows the scaling/shedding timeline interleaved with the engine events
that caused it.

Discipline (pinned by the dynlint fixture test): the loop task is held
and cancelled on ``stop()``, sources/actuators that block ride an
executor inside their own implementations, and a failing source or
actuator is logged and skipped — the loop itself never dies to one bad
cycle.
"""

from __future__ import annotations

import asyncio
import dataclasses
import inspect
import logging
import time
from typing import Callable, Dict, List, Mapping, Optional, Sequence

from ..telemetry.flight import FlightRecorder, flight_recorder
from ..telemetry.registry import MetricsRegistry
from .policy import Action, AdmissionAction, RebalanceAction, ScaleAction, SlaPolicy
from .signals import SignalStore

logger = logging.getLogger(__name__)

SignalSource = Callable[[], Mapping[str, float]]


@dataclasses.dataclass
class PlannerConfig:
    interval_s: float = 2.0


class Planner:
    """Drives one policy against pluggable sources and actuators."""

    def __init__(
        self,
        policy: Optional[SlaPolicy] = None,
        sources: Optional[Sequence[SignalSource]] = None,
        actuators: Optional[Sequence] = None,
        config: Optional[PlannerConfig] = None,
        signals: Optional[SignalStore] = None,
        replicas: Optional[Callable[[], Mapping[str, int]]] = None,
        registry: Optional[MetricsRegistry] = None,
        flight: Optional[FlightRecorder] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.policy = policy or SlaPolicy(clock=clock)
        self.sources: List[SignalSource] = list(sources or [])
        self.actuators: List = list(actuators or [])
        self.config = config or PlannerConfig()
        self.signals = signals or SignalStore(clock=clock)
        self._replicas_fn = replicas
        self.flight = flight if flight is not None else flight_recorder()
        self.clock = clock
        self._task: Optional[asyncio.Task] = None
        self.actions_applied: List[Action] = []  # audit trail for tests

        self.registry = registry or MetricsRegistry()
        self._actions_c = self.registry.counter(
            "dynamo_planner_actions_total",
            "Planner actions by kind=scale_up|scale_down|rebalance|"
            "admission and applied=true|false",
        )
        self._cycles_c = self.registry.counter(
            "dynamo_planner_cycles_total",
            "Planner observe→decide→actuate cycles",
        )
        self._replica_target = self.registry.gauge(
            "dynamo_planner_replica_target_replicas",
            "Planner's current replica target, by role=",
        )
        self.registry.callback_gauge(
            "dynamo_planner_shed_level_depth",
            "Priority classes currently shed from the bottom (policy)",
            # dynrace: domain(executor)
            lambda: self.policy.shed_level,
        )
        self.registry.callback_gauge(
            "dynamo_planner_local_prefill_threshold_tokens",
            "Policy's current disagg local/remote prefill threshold",
            # dynrace: domain(executor)
            lambda: self.policy.local_prefill_length,
        )

    # ---------- wiring ----------

    def add_source(self, source: SignalSource) -> None:
        self.sources.append(source)

    def add_actuator(self, actuator) -> None:
        self.actuators.append(actuator)

    # ---------- lifecycle ----------

    def start(self, spawn=asyncio.create_task) -> "Planner":
        self._task = spawn(self._loop())
        return self

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.step()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("planner cycle failed")
            await asyncio.sleep(self.config.interval_s)

    # ---------- one cycle ----------

    async def _current_replicas(self) -> Mapping[str, int]:
        """role → replica count, from the configured provider or the
        first actuator that can report. Providers may be sync (pure CR
        reads) or async (REST lookups riding an executor)."""
        if self._replicas_fn is not None:
            r = self._replicas_fn()
            return (await r) if inspect.isawaitable(r) else r
        for actuator in self.actuators:
            fn = getattr(actuator, "replicas", None)
            if fn is not None:
                try:
                    r = fn()
                    return (await r) if inspect.isawaitable(r) else r
                except Exception:
                    logger.debug("replica lookup failed", exc_info=True)
        return {}

    async def step(self) -> List[Action]:
        """One observe→decide→actuate pass; returns the emitted actions
        (applied or not) so callers/tests can drive the loop manually."""
        self._cycles_c.inc()
        t = self.clock()
        for source in self.sources:
            try:
                self.signals.observe_many(source() or {}, t=t)
            except Exception:
                logger.exception("planner signal source failed")
        actions = self.policy.decide(
            self.signals, await self._current_replicas())
        for action in actions:
            applied = await self._dispatch(action)
            self._record(action, applied)
            if applied:
                self.actions_applied.append(action)
            else:
                # no actuator claimed it (or the actuator failed): undo
                # the pacing state the decision committed so the policy
                # retries instead of believing a change that never landed
                self.policy.rollback(action)
        return actions

    async def _dispatch(self, action: Action) -> bool:
        for actuator in self.actuators:
            try:
                if await actuator.apply(action):
                    return True
            except Exception:
                logger.exception("actuator %s failed on %s",
                                 type(actuator).__name__, action)
        return False

    def _record(self, action: Action, applied: bool) -> None:
        applied_s = "true" if applied else "false"
        if isinstance(action, ScaleAction):
            self._actions_c.inc(kind=f"scale_{action.direction}",
                                applied=applied_s)
            if applied:
                self._replica_target.set(
                    action.target_replicas, role=action.role)
            self.flight.record(
                "planner.action", action="scale", role=action.role,
                from_replicas=action.current_replicas,
                to_replicas=action.target_replicas,
                applied=applied, reason=action.reason,
            )
        elif isinstance(action, RebalanceAction):
            self._actions_c.inc(kind="rebalance", applied=applied_s)
            self.flight.record(
                "planner.action", action="rebalance",
                max_local_prefill_length=action.max_local_prefill_length,
                max_prefill_queue_size=action.max_prefill_queue_size,
                applied=applied, reason=action.reason,
            )
        elif isinstance(action, AdmissionAction):
            self._actions_c.inc(kind="admission", applied=applied_s)
            self.flight.record(
                "planner.action", action="admission",
                shed_level=action.shed_level, limit=action.limit,
                applied=applied, reason=action.reason,
            )
        if not applied:
            logger.warning("planner action had no actuator: %s", action)
        else:
            logger.info("planner action applied: %s", action)


def aggregator_source(aggregator) -> SignalSource:
    """KvMetricsAggregator → planner signals: pool-level decode slot
    occupancy, waiting depth, and KV usage across scraped workers."""

    def snapshot() -> Dict[str, float]:
        endpoints = getattr(aggregator, "endpoints", {})
        if not endpoints:
            return {}
        active = sum(m.request_active_slots for m in endpoints.values())
        total = sum(m.request_total_slots for m in endpoints.values())
        kv_active = sum(m.kv_active_blocks for m in endpoints.values())
        kv_total = sum(m.kv_total_blocks for m in endpoints.values())
        return {
            "decode.slot_busy_ratio": active / total if total else 0.0,
            "decode.waiting": float(sum(
                m.num_requests_waiting for m in endpoints.values())),
            "kv.usage_ratio": kv_active / kv_total if kv_total else 0.0,
        }

    return snapshot


def slo_source(tracker) -> SignalSource:
    """A telemetry.slo.SloTracker → planner signals: rolling-window
    attainment fractions + goodput rate under the ``slo.*`` names
    policy.py consults (SIG_SLO_*). The edge's user-visible-latency
    view of saturation."""
    return tracker.snapshot


def engine_metrics_source(metrics_fn) -> SignalSource:
    """A single engine's ``metrics()`` dict (scheduler ForwardPassMetrics
    shape + coordinator extras) → planner signals. The in-process path
    for an ``in=http out=jax`` frontend running its own planner."""

    def snapshot() -> Dict[str, float]:
        m = metrics_fn() or {}
        total = m.get("request_total_slots") or 0
        active = m.get("request_active_slots") or 0
        kv_total = m.get("kv_total_blocks") or 0
        kv_active = m.get("kv_active_blocks") or 0
        out = {
            "decode.slot_busy_ratio": active / total if total else 0.0,
            "decode.waiting": float(m.get("num_requests_waiting") or 0),
            "kv.usage_ratio": kv_active / kv_total if kv_total else 0.0,
        }
        if "prefill_queue_depth" in m:
            out["prefill.queue_depth"] = float(m["prefill_queue_depth"])
        return out

    return snapshot
