"""Standalone /metrics listener for processes with no HTTP frontend.

The OpenAI frontend renders its registry on the service's own
``GET /metrics``; router processors and token-level workers serve
dyn:// traffic only, so their instruments (per-worker scraped load,
routing decisions, scheduler/KV internals) need a sidecar exposition
port — enabled with ``--metrics-port`` (0 = off).
"""

from __future__ import annotations

import logging
from typing import Optional

from aiohttp import web

from .registry import MetricsRegistry

logger = logging.getLogger(__name__)


class MetricsServer:
    """Minimal aiohttp app: GET /metrics → registry exposition."""

    def __init__(self, registry: MetricsRegistry,
                 host: str = "0.0.0.0", port: int = 9090,
                 routes=None):
        self.registry = registry
        self.host = host
        self.port = port
        self.app = web.Application()
        self.app.router.add_get("/metrics", self.handle_metrics)
        # extra (method, path, handler) routes: the hub/planner sidecar
        # serves /fleet/* next to its exposition without a full frontend
        for method, path, handler in routes or []:
            self.app.router.add_route(method, path, handler)
        self._runner: Optional[web.AppRunner] = None

    async def handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(
            text=self.registry.render(),
            content_type="text/plain", charset="utf-8",
        )

    async def start(self) -> "MetricsServer":
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        logger.info("metrics exposition on %s:%d/metrics", self.host, self.port)
        return self

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


async def maybe_start_metrics_server(
    registry: Optional[MetricsRegistry], port: int, host: str = "0.0.0.0",
    routes=None,
) -> Optional[MetricsServer]:
    """Start a sidecar exposition iff a registry exists and a port was
    requested — dyn:// roles call this unconditionally."""
    if registry is None or not port:
        return None
    return await MetricsServer(registry, host, port, routes=routes).start()
