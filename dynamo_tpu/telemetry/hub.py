"""Fleet telemetry hub: cluster-wide /metrics scrape + rollups.

The repo's observability so far is *per-process*: every role renders a
Prometheus exposition (frontend on the service port, dyn:// workers on
``--metrics-port`` sidecars), and a fleet view required an external
Prometheus. The :class:`FleetHub` is the in-cluster pane: a
discovery-driven scraper that pulls every process's exposition into
bounded per-worker :class:`~dynamo_tpu.telemetry.history.MetricHistory`
rings and serves

- ``GET /fleet/metrics`` — per-family rollups (sum/max/avg by role,
  counter rates over the window), and
- ``GET /fleet/workers`` — the per-worker operational row: KV
  utilization, busy ratio, roofline fraction, SLO attainment, drain
  state, watchdog trips, scrape liveness — what ``scripts/dynamotop.py``
  renders live.

Targets come from three places, composable: a static list (``--hub-
target role=url``), in-process registries (the ``in=http`` frontend
scrapes itself and its engine with zero HTTP), and the discovery plane —
workers that start a metrics sidecar register its URL under
``{ns}/telemetry/metrics/...`` (lease-scoped, so a dead worker's target
vanishes with its lease), the same pattern the migration receivers use.

The hub is also a planner signal source (``signal_source()``): fleet-
level saturation — mean busy ratio, mean KV usage, summed waiting,
summed watchdog trips, windowed SLO attainment — lands in the
SignalStore under the SAME ``decode.*``/``kv.*``/``slo.*`` names
policy.py already consults, so :class:`SlaPolicy` decisions ride the
whole pool instead of one process's scrape.

Discipline (pinned by tests/test_dynlint.py): the scrape task is held
and cancelled on ``stop()``, exposition parsing rides the executor, and
one unreachable target is counted and skipped — never fatal.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Awaitable, Callable, Dict, List, Optional

from .exposition import parse_exposition
from .history import MetricHistory
from .registry import MetricsRegistry

logger = logging.getLogger(__name__)

# discovery keys for metrics sidecars: {ns}/telemetry/metrics/{role}/{instance}
METRICS_ENDPOINT_PREFIX = "telemetry/metrics"

# how long a vanished target's last-known rows stay visible (marked
# down) before the hub forgets the worker entirely
DEFAULT_RETAIN_S = 120.0


def metrics_endpoint_key(namespace: str, role: str, instance: str) -> str:
    return f"{namespace}/{METRICS_ENDPOINT_PREFIX}/{role}/{instance}"


async def register_metrics_endpoint(drt, namespace: str, role: str,
                                    instance: str, url: str) -> None:
    """Advertise this process's /metrics sidecar in the discovery plane
    (lease-scoped: the target disappears with the worker's lease)."""
    import msgpack

    lease = await drt.discovery.primary_lease()
    await drt.discovery.kv_put(
        metrics_endpoint_key(namespace, role, instance),
        msgpack.packb({"url": url, "role": role, "name": instance},
                      use_bin_type=True),
        lease_id=lease.id,
    )


def discovery_targets(drt, namespace: str) -> Callable[[], Awaitable[List[dict]]]:
    """A hub ``discover`` callable over the discovery plane's registered
    sidecars (see :func:`register_metrics_endpoint`)."""
    import msgpack

    prefix = f"{namespace}/{METRICS_ENDPOINT_PREFIX}/"

    async def discover() -> List[dict]:
        kvs = await drt.discovery.kv_get_prefix(prefix)
        out = []
        for v in kvs.values():
            try:
                out.append(msgpack.unpackb(v, raw=False))
            except Exception:
                logger.warning("malformed metrics-endpoint record skipped",
                               exc_info=True)
        return out

    return discover


def parse_target_flag(spec: str) -> dict:
    """``role=url`` (or a bare url, role "worker") → target dict; the
    instance name defaults to the url's host:port."""
    role, sep, url = spec.partition("=")
    if not sep:
        role, url = "worker", spec
    role = role.strip() or "worker"
    url = url.strip()
    if "://" not in url:
        url = f"http://{url}"
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    name = url.split("://", 1)[1].split("/", 1)[0]
    return {"url": url, "role": role, "name": name}


class _Worker:
    """One scraped process: its history rings + scrape liveness."""

    __slots__ = ("name", "role", "url", "history", "last_ok_t",
                 "last_attempt_t", "last_error", "seen_t")

    def __init__(self, name: str, role: str, url: Optional[str],
                 history: MetricHistory):
        self.name = name
        self.role = role
        self.url = url  # None for in-process registries
        self.history = history
        self.last_ok_t: Optional[float] = None
        self.last_attempt_t: Optional[float] = None
        self.last_error: Optional[str] = None
        self.seen_t: float = 0.0  # last time the target list contained it


class FleetHub:
    """Scrapes the fleet into history rings; serves rollups."""

    def __init__(
        self,
        targets: Optional[List[dict]] = None,
        discover: Optional[Callable[[], Awaitable[List[dict]]]] = None,
        interval_s: float = 2.0,
        timeout_s: float = 1.5,
        history_window_s: float = 600.0,
        history_max_samples: int = 512,
        retain_s: float = DEFAULT_RETAIN_S,
        registry: Optional[MetricsRegistry] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.static_targets = list(targets or [])
        self.discover = discover
        self.interval_s = max(0.05, interval_s)
        self.timeout_s = timeout_s
        self.history_window_s = history_window_s
        self.history_max_samples = history_max_samples
        self.retain_s = retain_s
        self.clock = clock
        self._workers: Dict[str, _Worker] = {}
        self._locals: Dict[str, tuple] = {}  # name → (role, registry)
        self._task: Optional[asyncio.Task] = None
        self._session = None  # aiohttp.ClientSession, lazy

        self.registry = registry or MetricsRegistry()
        self._scrapes_c = self.registry.counter(
            "dynamo_hub_scrapes_total",
            "Hub scrape attempts, labelled role= and outcome=ok|error",
        )
        self._scrape_hist = self.registry.histogram(
            "dynamo_hub_scrape_duration_seconds",
            "One target's fetch+parse+ingest wall time",
        )
        self.registry.callback_gauge(
            "dynamo_hub_fleet_workers_replicas",
            "Workers the hub currently tracks, labelled role= and "
            "up=true|false (scrape liveness)",
            self._worker_counts,
        )
        self.registry.callback_gauge(
            "dynamo_hub_fleet_busy_ratio",
            "Fleet mean decode slot occupancy, by role= (the hub-side "
            "rollup a Prometheus avg() should agree with — grafana "
            "panel 25 plots both)",
            # dynrace: domain(executor)
            lambda: self._rollup_gauge("dynamo_scheduler_slot_occupancy_ratio"),
        )
        self.registry.callback_gauge(
            "dynamo_hub_fleet_kv_usage_ratio",
            "Fleet mean KV block usage, by role=",
            # dynrace: domain(executor)
            lambda: self._rollup_gauge("dynamo_kv_block_usage_ratio"),
        )
        self.registry.callback_gauge(
            "dynamo_hub_history_series_depth",
            "History-ring series held across all tracked workers",
            # dynrace: domain(executor)
            lambda: sum(w.history.series_count()
                        for w in list(self._workers.values())),
        )

    # ---------- wiring ----------

    def add_local(self, name: str, role: str, registry) -> None:
        """Scrape an in-process registry on the same cadence (the
        frontend's own exposition, an in-process engine) — no HTTP."""
        self._locals[name] = (role, registry)

    # ---------- lifecycle ----------

    def start(self, spawn=None) -> "FleetHub":
        if self._task is None:
            spawn = spawn or asyncio.get_running_loop().create_task
            self._task = spawn(self._loop())
        return self

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        session, self._session = self._session, None
        if session is not None:
            await session.close()

    async def _loop(self) -> None:
        while True:
            try:
                await self.scrape_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("hub scrape cycle failed; continuing")
            await asyncio.sleep(self.interval_s)

    # ---------- scraping ----------

    async def _target_list(self) -> List[dict]:
        targets = list(self.static_targets)
        if self.discover is not None:
            try:
                targets.extend(await self.discover() or [])
            except Exception:
                logger.warning("hub target discovery failed; scraping "
                               "last known pool", exc_info=True)
                # keep every previously-seen remote target alive
                targets.extend(
                    {"url": w.url, "role": w.role, "name": w.name}
                    for w in self._workers.values()
                    if w.url is not None
                    and not any(t.get("name") == w.name
                                for t in targets)
                )
        return targets

    def _worker_for(self, name: str, role: str,
                    url: Optional[str]) -> _Worker:
        w = self._workers.get(name)
        if w is None:
            w = self._workers[name] = _Worker(
                name, role, url,
                MetricHistory(window_s=self.history_window_s,
                              max_samples=self.history_max_samples,
                              clock=self.clock),
            )
        w.role = role
        w.url = url if url is not None else w.url
        w.seen_t = self.clock()
        return w

    async def scrape_once(self) -> None:
        targets = await self._target_list()
        jobs = []
        for t in targets:
            name = t.get("name") or t.get("url")
            if not name or not t.get("url"):
                continue
            w = self._worker_for(name, t.get("role") or "worker", t["url"])
            jobs.append(self._scrape_http(w))
        for name, (role, registry) in self._locals.items():
            w = self._worker_for(name, role, None)
            jobs.append(self._scrape_local(w, registry))
        if jobs:
            await asyncio.gather(*jobs)
        # forget workers that left the target set long enough ago that
        # their last-known rows stopped being useful
        cutoff = self.clock() - self.retain_s
        for name in [n for n, w in self._workers.items()
                     if w.seen_t < cutoff]:
            del self._workers[name]

    async def _scrape(self, w: _Worker, fetch) -> None:
        """Shared attempt/error/success bookkeeping around one target's
        exposition fetch (HTTP or in-process render)."""
        t0 = self.clock()
        w.last_attempt_t = t0
        try:
            text = await fetch()
            await self._ingest(w, text)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # one sick target must not take the fleet pane down — count
            # it, keep its history (the curve UP TO the failure is the
            # interesting part), and let /fleet/workers show it down
            w.last_error = repr(e)
            self._scrapes_c.inc(role=w.role, outcome="error")
            logger.debug("hub scrape of %s (%s) failed: %s",
                         w.name, w.url or "local", e)
        else:
            w.last_ok_t = self.clock()
            w.last_error = None
            self._scrapes_c.inc(role=w.role, outcome="ok")
            self._scrape_hist.observe(self.clock() - t0)

    async def _scrape_http(self, w: _Worker) -> None:
        import aiohttp

        async def fetch() -> str:
            if self._session is None:
                self._session = aiohttp.ClientSession()
            timeout = aiohttp.ClientTimeout(total=self.timeout_s)
            async with self._session.get(w.url, timeout=timeout) as resp:
                resp.raise_for_status()
                return await resp.text()

        await self._scrape(w, fetch)

    async def _scrape_local(self, w: _Worker, registry) -> None:
        async def fetch() -> str:
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None, registry.render)

        await self._scrape(w, fetch)

    async def _ingest(self, w: _Worker, text: str) -> None:
        loop = asyncio.get_running_loop()
        # parsing a large exposition is the scrape's CPU cost — executor
        families = await loop.run_in_executor(None, parse_exposition, text)
        w.history.ingest(families)

    # ---------- rollups ----------
    #
    # Read-side methods run OFF the event loop too: the /fleet handlers
    # ride the executor, and the callback gauges above fire inside any
    # executor-side registry.render (the sidecar server, the hub's own
    # local scrape). The scrape loop is the only writer; readers iterate
    # GIL-atomic list() snapshots of _workers and never mutate history
    # (see telemetry/history.py's threading note), so a concurrent
    # scrape-side insert/expire can't raise mid-iteration.

    def _up(self, w: _Worker) -> bool:
        if w.last_ok_t is None:
            return False
        return (self.clock() - w.last_ok_t) <= max(
            3 * self.interval_s, self.timeout_s)

    # registry render callback: runs wherever /metrics renders (loop
    # handler, hub executor offload, flight-dump thread) — reads must be
    # snapshot-safe
    # dynrace: domain(executor)
    def _worker_counts(self):
        counts: Dict[tuple, int] = {}
        for w in list(self._workers.values()):
            key = (w.role, "true" if self._up(w) else "false")
            counts[key] = counts.get(key, 0) + 1
        return [({"role": role, "up": up}, n)
                for (role, up), n in sorted(counts.items())]

    # dynrace: domain(executor)
    def _rollup_gauge(self, name: str):
        by_role: Dict[str, List[float]] = {}
        for w in list(self._workers.values()):
            if not self._up(w):
                # a wedged worker's last scrape stays readable in its
                # /fleet/workers row (marked down) but must not silently
                # steer a fleet AVERAGE for up to history_window_s
                continue
            v = w.history.latest(name)
            if v is not None:
                by_role.setdefault(w.role, []).append(v)
        return [({"role": role}, sum(vals) / len(vals))
                for role, vals in sorted(by_role.items())]

    def fleet_metrics(self, window_s: Optional[float] = None,
                      prefix: str = "dynamo_") -> dict:
        """Every family's sum/max/avg by role over UP workers
        (per-worker values are the worker's label-set sum), plus
        windowed per-second rates for cumulative series only — a
        gauge's slope reported under the same key would read as an
        event rate. The ``GET /fleet/metrics`` body."""
        families: Dict[str, dict] = {}
        for w in list(self._workers.values()):
            if not self._up(w):
                continue  # same staleness rule as _rollup_gauge
            # single pass per worker: this endpoint walks every name of
            # every worker on dynamotop's poll cadence, so per-name
            # series scans would go quadratic in series count
            summaries = w.history.name_summaries(window_s=window_s,
                                                 prefix=prefix)
            for name, summ in summaries.items():
                v = summ["latest"]
                fam = families.setdefault(name, {"roles": {}})
                roles = fam["roles"]
                entry = roles.setdefault(
                    w.role, {"sum": 0.0, "max": None, "workers": 0})
                entry["sum"] += v
                entry["max"] = v if entry["max"] is None else max(
                    entry["max"], v)
                entry["workers"] += 1
                if summ["kind"] == "counter":
                    entry["rate_per_s"] = entry.get(
                        "rate_per_s", 0.0) + summ["rate"]
        for fam in families.values():
            for entry in fam["roles"].values():
                entry["avg"] = entry["sum"] / entry["workers"]
        return {
            "time": time.time(),
            "window_s": window_s if window_s is not None
            else self.history_window_s,
            "families": families,
        }

    def fleet_workers(self, slo_window_s: float = 60.0) -> dict:
        """Per-worker operational rows — the ``GET /fleet/workers`` body
        and dynamotop's table."""
        rows = []
        now = self.clock()
        for w in sorted(list(self._workers.values()), key=lambda x: x.name):
            hist = w.history
            # slo="request" is the per-request conjunction (met EVERY
            # configured SLO) — blending the ttft/itl dimension series
            # would overstate attainment vs the SlaPolicy floor
            attained = hist.rate("dynamo_slo_attainment_total",
                                 {"slo": "request", "met": "true"},
                                 window_s=slo_window_s)
            judged = hist.rate("dynamo_slo_attainment_total",
                               {"slo": "request"}, window_s=slo_window_s)
            draining = hist.latest("dynamo_scheduler_draining_info")
            # the model this worker serves (multi-model fleet): workers
            # stamp dynamo_registry_model_info{model=} on their registry
            model = None
            for labels, _v in hist.samples("dynamo_registry_model_info"):
                if labels.get("model"):
                    model = labels["model"]
                    break
            row = {
                "name": w.name,
                "role": w.role,
                "model": model,
                "url": w.url,
                "up": self._up(w),
                "scrape_age_s": (
                    round(now - w.last_ok_t, 3)
                    if w.last_ok_t is not None else None
                ),
                "error": w.last_error,
                "kv_usage_ratio": hist.latest("dynamo_kv_block_usage_ratio"),
                "kv_active_blocks": hist.latest("dynamo_kv_active_blocks"),
                "busy_ratio": hist.latest(
                    "dynamo_scheduler_slot_occupancy_ratio"),
                "active_slots": hist.latest("dynamo_scheduler_active_slots"),
                "waiting": hist.latest("dynamo_scheduler_waiting_requests"),
                "roofline_fraction": hist.latest(
                    "dynamo_engine_roofline_fraction"),
                # prefix-hit view, fabric-aware: the local two-tier hit
                # ratio PLUS the datacenter-cache activity — committed
                # remote pulls and cold-tier rehydrates count tokens the
                # fleet never recomputed even though no local tier held
                # them (None = the worker runs no fabric)
                "prefix_hit_ratio": hist.latest(
                    "dynamo_kv_prefix_hit_ratio"),
                "prefix_pulls_per_s": (
                    round(hist.rate(
                        "dynamo_kv_fabric_prefix_pull_total",
                        {"outcome": "committed"},
                        window_s=slo_window_s), 3)
                    if hist.latest(
                        "dynamo_kv_fabric_prefix_pull_total") is not None
                    else None
                ),
                "cold_hits_per_s": (
                    round(hist.rate(
                        "dynamo_kv_fabric_cold_tier_hits_total",
                        window_s=slo_window_s), 3)
                    if hist.latest(
                        "dynamo_kv_fabric_cold_tier_hits_total")
                    is not None else None
                ),
                "slo_attainment": (
                    attained / judged if judged else None
                ),
                "draining": bool(draining) if draining is not None else None,
                "watchdog_trips": hist.latest("dynamo_watchdog_trips_total"),
                "restarts": hist.latest("dynamo_engine_restarts_total"),
                "incidents": hist.latest("dynamo_incidents_total"),
                # None = no HTTP metrics at all; 0.0 = a real flatline
                # (exactly the incident-time signal the pane exists for)
                "requests_per_s": (
                    round(hist.rate("dynamo_http_service_requests_total",
                                    window_s=slo_window_s), 3)
                    if hist.latest(
                        "dynamo_http_service_requests_total") is not None
                    else None
                ),
            }
            rows.append(row)
        return {"time": time.time(), "workers": rows}

    # ---------- planner signal source ----------

    def signal_source(self) -> Callable[[], Dict[str, float]]:
        """Fleet-level saturation under the existing policy vocabulary
        (planner/policy.py SIG_*): the planner consults the POOL, not
        whichever single scrape it happens to sit next to."""

        # the planner polls this from its own loop/executor context
        # dynrace: domain(executor)
        def snapshot() -> Dict[str, float]:
            busy: List[float] = []
            kv: List[float] = []
            waiting = 0.0
            have_waiting = False
            trips = 0.0
            have_trips = False
            attained = judged = 0.0
            for w in list(self._workers.values()):
                if not self._up(w):
                    continue
                hist = w.history
                b = hist.latest("dynamo_scheduler_slot_occupancy_ratio")
                if b is not None:
                    busy.append(b)
                k = hist.latest("dynamo_kv_block_usage_ratio")
                if k is not None:
                    kv.append(k)
                q = hist.latest("dynamo_scheduler_waiting_requests")
                if q is not None:
                    waiting += q
                    have_waiting = True
                t = hist.latest("dynamo_watchdog_trips_total")
                if t is not None:
                    trips += t
                    have_trips = True
                attained += hist.rate(
                    "dynamo_slo_attainment_total",
                    {"slo": "request", "met": "true"}, window_s=60.0)
                judged += hist.rate("dynamo_slo_attainment_total",
                                    {"slo": "request"}, window_s=60.0)
            out: Dict[str, float] = {}
            if busy:
                out["decode.slot_busy_ratio"] = sum(busy) / len(busy)
            if kv:
                out["kv.usage_ratio"] = sum(kv) / len(kv)
            if have_waiting:
                out["decode.waiting"] = waiting
            if have_trips:
                # cumulative (reset-adjusted) fleet trip total: the
                # policy's delta() over this series is trips-in-window
                out["watchdog.trips"] = trips
            if judged > 0:
                out["slo.attainment"] = attained / judged
            return out

        return snapshot

    # ---------- aiohttp handlers (mounted by HttpService/MetricsServer) ----------

    async def handle_fleet_metrics(self, request):
        from aiohttp import web

        window = None
        raw = request.query.get("window")
        if raw:
            try:
                window = max(1.0, float(raw))
            except ValueError:
                return web.json_response({"error": "bad window"}, status=400)
        prefix = request.query.get("prefix", "dynamo_")
        loop = asyncio.get_running_loop()
        body = await loop.run_in_executor(
            None, lambda: self.fleet_metrics(window, prefix))
        return web.json_response(body)

    async def handle_fleet_workers(self, request):
        from aiohttp import web

        loop = asyncio.get_running_loop()
        body = await loop.run_in_executor(None, self.fleet_workers)
        return web.json_response(body)
