"""Incident recorder: trigger-driven capture bundles.

The flight recorder, history rings, and trace store all hold evidence —
but until now an operator had to *pull* it, manually, after noticing a
problem, and by then PR 8's RecoveryController has usually drained and
respawned the wedged engine and the in-memory evidence is gone. The
:class:`IncidentRecorder` flips the direction: it subscribes to the
degradation edges the repo already emits —

- ``StallWatchdog.add_trip_listener`` (decode_stall / no_throughput /
  event_loop_lag),
- ``RecoveryController.add_drain_listener`` (the recovery ladder
  engaging for any non-admin reason),
- SLO attainment falling through the policy floor (:func:`slo_probe`),
- a late-XLA-compile burst from the CompileTracker
  (:func:`late_compile_probe`),

and on an edge captures ONE correlated bundle to ``DYN_INCIDENT_DIR``:

- ``manifest.json`` — reason, trigger info, wall/monotonic stamps, the
  affected request id, what was (and wasn't) captured;
- ``flight.json`` — the full flight artifact
  (telemetry/watchdog.build_flight_artifact: ring, stacks, probes,
  request tables, metrics snapshot);
- ``history.json`` — the last N minutes of local metric history rings
  (telemetry/history.py — the curve INTO the incident, not one point);
- ``traces.json`` — the stitched traces of affected requests from the
  live TraceRecorders (ids correlated through the flight ring);
- optionally ``profile/`` — a ``jax.profiler`` capture window
  (``--incident-profile-s``; skipped cleanly when another capture holds
  the process-wide profiler lock).

Bundles are rate-limited (per-reason cooldown + a global min interval,
so one wedge that trips the watchdog AND engages recovery yields ONE
bundle) and deduped per (reason, request). Every decision is counted:
``dynamo_incidents_total{reason}`` / ``dynamo_incidents_suppressed_
total{reason}``. ``GET /debug/incidents`` lists and fetches bundles;
``scripts/flightdump.py --incident <dir>`` renders one offline.

Discipline (pinned by tests/test_dynlint.py): every capture task is
held until done, all bundle IO rides the executor, and a failing
capture is logged — detection must survive its own reporting.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import json
import logging
import os
import re
import time
from typing import Callable, Dict, List, Optional

from .history import MetricHistory

logger = logging.getLogger(__name__)

INCIDENT_DIR_ENV = "DYN_INCIDENT_DIR"
MANIFEST = "manifest.json"
_BUNDLE_RE = re.compile(r"^incident-\d+-\d+-[a-z0-9_]+$")


def incident_dir() -> Optional[str]:
    return os.environ.get(INCIDENT_DIR_ENV) or None


def _safe_reason(reason: str) -> str:
    return re.sub(r"[^a-z0-9_]+", "_", reason.lower()).strip("_") or "unknown"


@dataclasses.dataclass
class IncidentConfig:
    out_dir: Optional[str] = None     # None → DYN_INCIDENT_DIR at capture
    cooldown_s: float = 60.0          # per-reason re-trigger floor
    min_interval_s: float = 30.0      # global floor: one wedge, one bundle
    dedup_s: float = 300.0            # (reason, request) re-trigger floor:
    #                                   the SAME request re-tripping the
    #                                   SAME reason is noise long after the
    #                                   per-reason cooldown has cleared
    settle_s: float = 0.75            # trip → capture delay, so the drain
    #                                   outcome and just-finished traces
    #                                   land in the bundle too
    history_window_s: float = 300.0   # how far back history.json reaches
    max_bundles: int = 32             # oldest pruned beyond this
    max_traces: int = 16
    profile_s: float = 0.0            # >0: jax.profiler capture window


class IncidentRecorder:
    """Edge-triggered capture of correlated incident bundles."""

    def __init__(
        self,
        config: Optional[IncidentConfig] = None,
        history: Optional[MetricHistory] = None,
        registry=None,
        flight=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        from .flight import flight_recorder
        from .registry import MetricsRegistry

        self.config = config or IncidentConfig()
        self.history = history
        self.flight = flight if flight is not None else flight_recorder()
        self.clock = clock
        self.registry = registry or MetricsRegistry()
        self._captured_c = self.registry.counter(
            "dynamo_incidents_total",
            "Incident bundles captured, labelled reason= (decode_stall|"
            "no_throughput|event_loop_lag|recovery_drain|slo_floor|"
            "late_compile_burst|manual|...)",
        )
        self._suppressed_c = self.registry.counter(
            "dynamo_incidents_suppressed_total",
            "Incident triggers suppressed by per-reason cooldown, the "
            "global min interval, or (reason, request) dedup",
        )
        self._last_by_reason: Dict[str, float] = {}
        self._last_any: Optional[float] = None
        self._last_key: Dict[tuple, float] = {}
        self._tasks: set = set()
        self._probes: List[Callable[[], Optional[dict]]] = []
        self._probe_active: Dict[int, bool] = {}
        self._probe_task: Optional[asyncio.Task] = None
        self._seq = 0
        self.bundles: List[dict] = []   # manifests, newest last (tests)
        self.captures = 0
        self.suppressed = 0

    # ---------- trigger sources ----------

    def watch_watchdog(self, watchdog) -> None:
        """Capture on every watchdog trip (the trip's own flight dump is
        a point-in-time artifact; the bundle adds history + traces and
        survives the recovery that follows)."""

        def on_trip(info: dict) -> None:
            probe = info.get("probe") or {}
            self.trigger(
                info.get("reason", "watchdog"),
                request_id=None,
                stalled_for_s=info.get("stalled_for_s"),
                queue_depth=probe.get("queue_depth"),
                active=probe.get("active"),
            )

        watchdog.add_trip_listener(on_trip)

    def watch_recovery(self, controller) -> None:
        """Capture when the recovery ladder engages for a real failure.
        Admin drains (rolling updates) are operator-intended and do not
        produce incident bundles."""

        def on_drain(info: dict) -> None:
            if info.get("reason") == "admin":
                return
            self.trigger("recovery_drain", reason_detail=info.get("reason"),
                         hard=info.get("hard"))

        controller.add_drain_listener(on_drain)

    def add_probe(self, probe: Callable[[], Optional[dict]]) -> None:
        """Register an edge probe: a callable returning None while
        healthy and ``{"reason": ..., **info}`` while degraded. The poll
        loop fires on the False→True edge and re-arms on clear."""
        self._probes.append(probe)

    # ---------- lifecycle ----------

    def start(self, probe_interval_s: float = 5.0) -> "IncidentRecorder":
        if self._probe_task is None and self._probes:
            self._probe_task = asyncio.get_running_loop().create_task(
                self._probe_loop(max(0.02, probe_interval_s)),
                name="incident-probes")
        return self

    async def stop(self) -> None:
        task, self._probe_task = self._probe_task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        # let in-flight captures finish — an incident bundle racing
        # shutdown is exactly the evidence worth waiting a moment for
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)

    async def _probe_loop(self, interval_s: float) -> None:
        while True:
            for i, probe in enumerate(self._probes):
                try:
                    result = probe()
                except Exception:
                    logger.exception("incident probe failed; continuing")
                    continue
                if result:
                    if not self._probe_active.get(i):
                        self._probe_active[i] = True
                        info = dict(result)
                        reason = info.pop("reason", "probe")
                        self.trigger(reason, **info)
                else:
                    self._probe_active[i] = False
            await asyncio.sleep(interval_s)

    # ---------- the trigger ----------

    def trigger(self, reason: str, request_id: Optional[str] = None,
                **info) -> bool:
        """Rate-limited capture entry (sync; callable from any listener
        on the event loop). Returns whether a capture was scheduled."""
        reason = _safe_reason(reason)
        now = self.clock()
        suppressed_by = None
        last = self._last_by_reason.get(reason)
        if last is not None and now - last < self.config.cooldown_s:
            suppressed_by = "cooldown"
        elif (self._last_any is not None
              and now - self._last_any < self.config.min_interval_s):
            # one wedge trips the watchdog AND engages recovery within
            # seconds — the global floor folds those into ONE bundle
            suppressed_by = "min_interval"
        elif request_id is not None:
            key = (reason, request_id)
            last_k = self._last_key.get(key)
            if last_k is not None and now - last_k < self.config.dedup_s:
                suppressed_by = "dedup"
        if suppressed_by is not None:
            self.suppressed += 1
            self._suppressed_c.inc(reason=reason)
            self.flight.record("incident.suppressed", reason=reason,
                               by=suppressed_by, request_id=request_id)
            return False
        self._last_by_reason[reason] = now
        self._last_any = now
        if request_id is not None:
            self._last_key[(reason, request_id)] = now
        self._seq += 1
        task = asyncio.get_running_loop().create_task(
            self._capture(reason, request_id, info, self._seq),
            name=f"incident-capture-{reason}")
        self._tasks.add(task)

        def _done(t: asyncio.Task) -> None:
            self._tasks.discard(t)
            if not t.cancelled() and t.exception() is not None:
                logger.error("incident capture failed: %r", t.exception())

        task.add_done_callback(_done)
        return True

    # ---------- the capture ----------

    async def _capture(self, reason: str, request_id: Optional[str],
                       info: dict, seq: int) -> Optional[str]:
        from .flight import flight_recorder
        from .watchdog import build_flight_artifact

        if self.config.settle_s > 0:
            await asyncio.sleep(self.config.settle_s)
        loop = asyncio.get_running_loop()
        # the global ring → merge-all artifact (every registered engine's
        # ring contributes); an injected private ring (tests, multi-
        # recorder processes) is captured explicitly
        ring = None if self.flight is flight_recorder() else self.flight

        def _assemble():
            # executor-side on purpose: the artifact walks every ring
            # and thread stack, and the history snapshot materializes
            # up to max_series x max_samples points — evidence capture
            # fires exactly when the loop is already degraded and must
            # not extend the very stall it documents (history reads are
            # off-loop safe; see telemetry/history.py)
            artifact = build_flight_artifact(
                reason=f"incident:{reason}", flight=ring)
            history_snap = (
                self.history.snapshot(self.config.history_window_s)
                if self.history is not None else None
            )
            traces = self._affected_traces(artifact, request_id)
            return artifact, history_snap, traces

        artifact, history_snap, traces = await loop.run_in_executor(
            None, _assemble)
        manifest = {
            "version": 1,
            "reason": reason,
            "time": time.time(),
            "monotonic": self.clock(),
            "pid": os.getpid(),
            "request_id": request_id,
            "info": {k: v for k, v in info.items() if v is not None},
            "flight_events": len(artifact.get("events") or []),
            "history_series": len((history_snap or {}).get("series") or []),
            "traces": [t.get("request_id") for t in traces],
        }
        # payload files land first; the profiler window (if any) captures
        # INTO the bundle; the manifest lands last to mark it complete —
        # list_bundles treats a manifest-less dir as a capture in flight
        path = await loop.run_in_executor(
            None, self._write_payload, artifact, history_snap,
            traces, reason,
        )
        profile_note = await self._maybe_profile(path)
        if profile_note:
            manifest["profile"] = profile_note
        await loop.run_in_executor(
            None, self._finalize_bundle, path, manifest)
        manifest["path"] = path
        self.captures += 1
        self._captured_c.inc(reason=reason)
        self.bundles.append(manifest)
        self.flight.record("incident.captured", reason=reason,
                           request_id=request_id, path=path)
        if path:
            logger.error("INCIDENT [%s] bundle captured at %s "
                         "(%d events, %d traces)", reason, path,
                         manifest["flight_events"], len(traces))
        else:
            logger.error("INCIDENT [%s] captured in memory only — set "
                         "%s to persist bundles", reason, INCIDENT_DIR_ENV)
        return path

    def _affected_traces(self, artifact: dict,
                         request_id: Optional[str]) -> List[dict]:
        """Completed traces correlated with the incident: the triggering
        request plus every id the flight ring saw recently."""
        affected = set()
        if request_id:
            affected.add(request_id)
        for e in artifact.get("events") or []:
            for k in ("request_id", "trace_id"):
                if e.get(k):
                    affected.add(e[k])
        for src in artifact.get("sources") or []:
            for row in src.get("requests") or []:
                for k in ("request_id", "trace_id"):
                    if row.get(k):
                        affected.add(row[k])
        out = []
        for trace in artifact.get("traces") or []:
            rid = trace.get("request_id")
            if rid in affected:
                out.append(trace)
        return out[-self.config.max_traces:]

    async def _maybe_profile(self, bundle: Optional[str]) -> Optional[dict]:
        if self.config.profile_s <= 0:
            return None
        if not bundle:
            return {"skipped": "no incident dir configured"}
        from ..utils.profiling import CaptureBusyError, capture_trace_async

        try:
            # captured INSIDE the bundle (docs: "bundle anatomy" →
            # profile/), so pruning the bundle removes its multi-MB XLA
            # trace with it instead of orphaning it in the incident dir
            trace_dir = await capture_trace_async(
                os.path.join(bundle, "profile"), self.config.profile_s)
            return {"trace_dir": trace_dir,
                    "seconds": self.config.profile_s}
        except CaptureBusyError:
            # a manual /debug/profile (or a racing incident) holds the
            # process-wide profiler lock — skip, never crash mid-capture
            return {"skipped": "another profiler capture is in flight"}
        except Exception as e:
            logger.warning("incident profile capture failed: %s", e)
            return {"error": repr(e)}

    def _write_payload(self, artifact: dict, history_snap: Optional[dict],
                       traces: List[dict], reason: str) -> Optional[str]:
        """Blocking payload write (executor-side): the bundle dir + every
        file EXCEPT the manifest (see :meth:`_finalize_bundle`)."""
        out_dir = self.config.out_dir or incident_dir()
        if not out_dir:
            return None
        name = f"incident-{os.getpid()}-{time.monotonic_ns()}-{reason}"
        bundle = os.path.join(out_dir, name)
        os.makedirs(bundle, exist_ok=False)
        files = {"flight.json": artifact, "traces.json": traces}
        if history_snap is not None:
            files["history.json"] = history_snap
        for fname, payload in files.items():
            with open(os.path.join(bundle, fname), "w") as f:
                json.dump(payload, f, default=str, indent=1)
        return bundle

    def _finalize_bundle(self, bundle: Optional[str],
                         manifest: dict) -> None:
        """Blocking manifest write + prune (executor-side). The manifest
        lands LAST: its presence marks a complete bundle."""
        if not bundle:
            return
        files = [f for f in os.listdir(bundle) if f != MANIFEST]
        if os.path.isdir(os.path.join(bundle, "profile")):
            files = [f if f != "profile" else "profile/" for f in files]
        manifest["files"] = sorted([MANIFEST, *files])
        manifest["bundle"] = os.path.basename(bundle)
        with open(os.path.join(bundle, MANIFEST), "w") as f:
            json.dump(manifest, f, default=str, indent=1)
        self._prune_bundles(os.path.dirname(bundle))

    @staticmethod
    def _bundle_mtime(out_dir: str, name: str) -> float:
        """Chronological sort key: manifest mtime (= completion time),
        falling back to the dir's own for an in-flight capture. Bundle
        NAMES don't order — monotonic_ns isn't comparable across hosts
        sharing an incident volume, and a lexicographic sort would
        compare pid digits first (and break across digit-count
        boundaries), pruning fresh evidence while keeping stale."""
        for p in (os.path.join(out_dir, name, MANIFEST),
                  os.path.join(out_dir, name)):
            try:
                return os.path.getmtime(p)
            except OSError:
                continue
        return 0.0

    def _prune_bundles(self, out_dir: str) -> None:
        import shutil

        bundles = sorted(
            (d for d in os.listdir(out_dir)
             if _BUNDLE_RE.match(d)
             and os.path.isdir(os.path.join(out_dir, d))),
            key=lambda d: self._bundle_mtime(out_dir, d),
        )
        while len(bundles) > self.config.max_bundles:
            victim = bundles.pop(0)  # oldest completion first
            shutil.rmtree(os.path.join(out_dir, victim), ignore_errors=True)

    # ---------- listing / fetching ----------

    def list_bundles(self) -> List[dict]:
        """Manifests of every complete on-disk bundle, oldest first.
        Blocking (disk walk) — async callers use the executor."""
        out_dir = self.config.out_dir or incident_dir()
        if not out_dir or not os.path.isdir(out_dir):
            return []
        out = []
        for name in sorted(os.listdir(out_dir),
                           key=lambda d: self._bundle_mtime(out_dir, d)):
            if not _BUNDLE_RE.match(name):
                continue
            mpath = os.path.join(out_dir, name, MANIFEST)
            try:
                with open(mpath) as f:
                    out.append(json.load(f))
            except (OSError, json.JSONDecodeError):
                continue  # incomplete bundle (capture in flight)
        return out

    def load_bundle(self, bundle_id: str) -> Optional[dict]:
        """One bundle's manifest + payload files. Blocking — executor."""
        out_dir = self.config.out_dir or incident_dir()
        if not out_dir or not _BUNDLE_RE.match(bundle_id):
            return None
        return load_bundle_dir(os.path.join(out_dir, bundle_id))

    async def handle_debug_incidents(self, request):
        """GET /debug/incidents[?id=<bundle>] — list manifests, or fetch
        one bundle's full contents."""
        from aiohttp import web

        loop = asyncio.get_running_loop()
        bundle_id = request.query.get("id")
        if bundle_id:
            bundle = await loop.run_in_executor(
                None, self.load_bundle, bundle_id)
            if bundle is None:
                return web.json_response(
                    {"error": f"no bundle {bundle_id!r}"}, status=404)
            return web.json_response(bundle, dumps=lambda o: json.dumps(
                o, default=str))
        manifests = await loop.run_in_executor(None, self.list_bundles)
        return web.json_response({
            "dir": self.config.out_dir or incident_dir(),
            "bundles": manifests,
        })


def load_bundle_dir(path: str) -> Optional[dict]:
    """Read one bundle directory (manifest + payload files) — shared by
    the recorder's endpoint and scripts/flightdump.py --incident."""
    mpath = os.path.join(path, MANIFEST)
    try:
        with open(mpath) as f:
            out = {"manifest": json.load(f)}
    except (OSError, json.JSONDecodeError):
        return None
    for fname, key in (("flight.json", "flight"),
                       ("history.json", "history"),
                       ("traces.json", "traces")):
        fpath = os.path.join(path, fname)
        if os.path.exists(fpath):
            try:
                with open(fpath) as f:
                    out[key] = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                out[key] = None
                out.setdefault("errors", []).append(f"{fname}: {e}")
    return out


# ---------- edge-probe factories ----------


def slo_probe(tracker, floor: float = 0.9,
              min_requests: int = 5) -> Callable[[], Optional[dict]]:
    """Fires when windowed SLO attainment falls below ``floor`` — the
    same threshold SlaPolicy sheds on (slo_attainment_floor), so the
    bundle lands at the moment the planner starts reacting."""

    def probe() -> Optional[dict]:
        snap = tracker.snapshot() or {}
        attainment = snap.get("slo.attainment")
        if attainment is None:
            return None
        # a 1-request window breaching the floor is noise, not an incident
        judged = tracker.window_count()
        if judged < min_requests:
            return None
        if attainment < floor:
            return {"reason": "slo_floor", "attainment": round(attainment, 4),
                    "floor": floor, "window_requests": judged}
        return None

    return probe


def late_compile_probe(compiles, burst: int = 3, window_s: float = 60.0,
                       clock: Callable[[], float] = time.monotonic,
                       ) -> Callable[[], Optional[dict]]:
    """Fires when the CompileTracker records ``burst`` or more LATE
    compiles within ``window_s`` — the recompile-storm signal
    (docs/perf_tuning.md) escalated from a log line to a bundle."""
    marks: collections.deque = collections.deque()
    seen = {"count": 0}

    def probe() -> Optional[dict]:
        now = clock()
        late = compiles.late_compiles
        new = late - seen["count"]
        seen["count"] = late
        for _ in range(max(0, new)):
            marks.append(now)
        while marks and marks[0] < now - window_s:
            marks.popleft()
        if len(marks) >= burst:
            return {"reason": "late_compile_burst",
                    "late_compiles_in_window": len(marks),
                    "window_s": window_s}
        return None

    return probe
