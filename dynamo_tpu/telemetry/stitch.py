"""Cross-process trace stitching: per-hop clock offsets + one timeline.

A request that crosses the disagg plane (frontend → router → remote
prefill worker → transfer → decode engine, possibly → migration peer)
leaves span marks in every process it touches, each stamped against
that process's OWN clock. This module is the math that renders them on
one axis:

- **Span export.** Each process's :class:`~dynamo_tpu.runtime.engine.
  AsyncEngineContext` converts its monotonic span marks to wall-clock
  stamps (``export_spans``) and ships them back on an EXISTING response
  frame — the dial-back stream's ``end`` frame, the KV transfer plane's
  ``commit`` frame, the migration plane's ``mig_end`` frame. No new
  service, no extra round trip.
- **Offset estimation.** Wall clocks skew across hosts, so each hop's
  receiver estimates the remote−local clock offset NTP-style from the
  request/response timestamp pair it already has (`estimate_offset`).
  The estimate's error is bounded by half the NETWORK round trip — the
  remote processing time between ``recv_at`` and ``resp_sent_at`` drops
  out of the formula, so even a 2-minute remote prefill yields a
  millisecond-grade offset.
- **Stitching.** Remote span sets nest (the frontend holds the decode
  worker's set, which holds the prefill worker's set); offsets compose
  down the chain, and `stitched_timeline` flattens everything onto the
  trace-origin axis with the same closing-mark attribution local spans
  use (telemetry/tracing.span_breakdown).

Wire shape of one remote span set (msgpack/json-able)::

    {"source": "prefill_worker",
     "spans": [[name, wall_t], ...],     # remote wall-clock marks
     "recv_at": wall_t,                  # request received (remote clock)
     "resp_sent_at": wall_t,             # response sent (remote clock)
     "offset_s": float,                  # remote - local (folded locally)
     "rtt_s": float,                     # network-only round trip
     "children": [...]}                  # that process's OWN remote sets
"""

from __future__ import annotations

from typing import List, Optional, Tuple

# span sources deeper than this are dropped: a malicious/buggy frame
# must not recurse the stitcher to death
MAX_HOP_DEPTH = 8


def estimate_offset(sent_local: float, recv_remote: float,
                    resp_sent_remote: float,
                    resp_recv_local: float) -> Tuple[float, float]:
    """NTP-style per-hop clock offset from one request/response pair.

    Returns ``(offset, rtt)`` where ``offset`` is the estimated
    ``remote_clock - local_clock`` and ``rtt`` is the network-only round
    trip (total round trip minus the remote's processing time). The
    offset error is bounded by ``rtt / 2`` regardless of how long the
    remote held the request — asymmetric CLOCKS are corrected; only
    asymmetric network LEGS survive as error.
    """
    rtt = max(
        0.0,
        (resp_recv_local - sent_local) - (resp_sent_remote - recv_remote),
    )
    offset = (
        (recv_remote - sent_local) + (resp_sent_remote - resp_recv_local)
    ) / 2.0
    return offset, rtt


def estimate_offset_return_leg(resp_sent_remote: float,
                               resp_recv_local: float) -> float:
    """Offset estimate from the response leg alone, for hops whose
    forward "leg" is queue-mediated (remote prefill: submit enqueues,
    the worker dequeues whenever it gets there). The symmetric formula
    assumes both legs are network transits — a 4 s queue backlog would
    skew the estimate by ~2 s, misplacing every remote span in exactly
    the deep-queue trace the X-ray exists to diagnose. Using only
    ``resp_sent_remote − resp_recv_local`` bounds the error by the
    ONE-WAY response transit (estimate reads low by that transit),
    typically milliseconds regardless of queue depth."""
    return resp_sent_remote - resp_recv_local


def remote_span_set(source: str, spans: List, recv_at: float,
                    resp_sent_at: float, sent_local: float,
                    resp_recv_local: float,
                    children: Optional[List] = None,
                    queued_forward: bool = False) -> dict:
    """Fold one hop's exported spans into a local-clock-aware set.

    ``queued_forward`` marks hops where ``sent_local`` is a queue-submit
    time rather than a direct send: the offset then comes from the
    return leg alone (see :func:`estimate_offset_return_leg`) while the
    symmetric ``rtt`` is still reported as the conservative confidence
    envelope (the true error is only the one-way response transit).
    """
    offset, rtt = estimate_offset(
        sent_local, recv_at, resp_sent_at, resp_recv_local
    )
    if queued_forward:
        offset = estimate_offset_return_leg(resp_sent_at, resp_recv_local)
    return {
        "source": source,
        "spans": [[str(n), float(t)] for n, t in (spans or [])],
        "recv_at": float(recv_at),
        "resp_sent_at": float(resp_sent_at),
        "offset_s": round(offset, 6),
        "rtt_s": round(rtt, 6),
        "children": list(children or []),
    }


def _marks_to_spans(source: str, marks: List, t0: float,
                    offset: float) -> List[dict]:
    """[(name, remote_wall)] → closing-mark spans on the local axis.

    Same attribution as tracing.span_breakdown: span ``X`` covers the
    gap from the PREVIOUS mark to the moment ``X`` was stamped. The
    first mark opens the source's timeline (zero-length ``arrive``
    anchor is implicit in its offset).
    """
    out = []
    prev = None
    for name, wall in marks:
        start = float(wall) - offset - t0
        if prev is None:
            out.append({
                "source": source, "name": str(name),
                "start_s": round(start, 6), "duration_s": 0.0,
            })
        else:
            out.append({
                "source": source, "name": str(name),
                "start_s": round(prev, 6),
                "duration_s": round(max(0.0, start - prev), 6),
            })
        prev = start
    return out


def stitched_timeline(trace: dict) -> dict:
    """A completed trace (tracing.TraceRecorder shape) → one timeline.

    Returns ``{"sources": [...], "timeline": [...]}`` where every
    timeline row is ``{source, name, start_s, duration_s}`` on the
    TRACE-ORIGIN axis (the frontend's first mark = 0) and ``sources``
    lists each hop with its estimated clock offset and network rtt —
    the per-hop confidence bars of the rendering.
    """
    t0 = float(trace.get("t0_wall") or 0.0)
    rows: List[dict] = []
    sources: List[dict] = [{"source": "frontend", "offset_s": 0.0,
                            "rtt_s": 0.0}]
    for span in trace.get("spans") or []:
        rows.append({
            "source": "frontend", "name": span["name"],
            "start_s": span["offset_s"], "duration_s": span["duration_s"],
        })

    def walk(rs: dict, base_offset: float, depth: int) -> None:
        if depth > MAX_HOP_DEPTH:
            return
        offset = float(rs.get("offset_s") or 0.0) + base_offset
        source = str(rs.get("source") or "remote")
        sources.append({
            "source": source,
            "offset_s": round(offset, 6),
            "rtt_s": round(float(rs.get("rtt_s") or 0.0), 6),
        })
        rows.extend(_marks_to_spans(source, rs.get("spans") or [], t0,
                                    offset))
        for child in rs.get("children") or []:
            walk(child, offset, depth + 1)

    for rs in trace.get("remote") or []:
        walk(rs, 0.0, 1)
    rows.sort(key=lambda r: (r["start_s"], r["source"]))
    return {"sources": sources, "timeline": rows}


def timeline_gaps(timeline: List[dict], min_gap_s: float = 0.0) -> List[dict]:
    """Unattributed time: stretches covered by NO span of any source.

    The "where did my 900 ms go" tool: a stitched trace whose spans sum
    to 300 ms still has 600 ms of gaps — each returned row names the
    spans it falls between, so the gap is attributable to the hop
    boundary (queue transit, network, a process that stamped nothing).
    """
    if not timeline:
        return []
    covered_until = None
    gaps = []
    prev_row = None
    for row in sorted(timeline, key=lambda r: r["start_s"]):
        start, end = row["start_s"], row["start_s"] + row["duration_s"]
        if covered_until is not None and start - covered_until > min_gap_s:
            gaps.append({
                "start_s": round(covered_until, 6),
                "duration_s": round(start - covered_until, 6),
                "after": (f"{prev_row['source']}:{prev_row['name']}"
                          if prev_row else ""),
                "before": f"{row['source']}:{row['name']}",
            })
        if covered_until is None or end >= covered_until:
            covered_until = end
            prev_row = row
    return gaps
