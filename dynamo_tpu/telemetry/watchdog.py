"""Stall watchdog + flight-artifact dumps.

Answers "why is the engine stuck RIGHT NOW": an asyncio task that
samples the scheduler's loop heartbeat, the event loop's own lag, and
queue-depth-vs-throughput, and on a trip dumps a **flight artifact** —
the flight ring (telemetry/flight.py), all-thread stacks, a metrics
snapshot, and the active request table — to ``DYN_FLIGHT_DIR`` while
incrementing ``dynamo_watchdog_trips_total{reason}``. The same dump is
reachable on demand at ``GET /debug/flight`` (http/service.py) and via
``SIGUSR2`` (install_signal_dump).

Trip conditions (each with its own ``reason`` label):

- ``decode_stall`` — work is pending (active slots or queued requests)
  but the scheduler loop's heartbeat stamp is older than ``stall_s``.
  The loop stamps the heartbeat at the top of EVERY pass, so a healthy
  loop that is merely *waiting* (idle wake, remote-prefill poll, chunked
  prefill between chunks) stays fresh; only a loop wedged *inside* a
  pass — a hung Mosaic compile, a host sync stuck on a dead device, an
  executor job that never returns — goes stale.
- ``no_throughput`` — requests are queued but the scheduler has not
  dispatched a single step for ``stall_s`` while its heartbeat stays
  fresh: the loop is spinning without making progress (e.g. leaked
  slots starving admission).
- ``event_loop_lag`` — the sampled sleep drift exceeded ``stall_s``:
  something blocked the event loop itself for that long (the drift is
  always exported as ``dynamo_runtime_event_loop_lag_seconds``).

After a trip the watchdog re-arms only once the tripping condition
clears, so a persistent wedge produces one artifact, not one per
sampling interval.

The watchdog holds its task handle and cancels it on ``stop()`` (the
task-leak rule), and every filesystem write rides ``run_in_executor``
(the async-blocking rule) — both pinned zero-finding by
tests/test_dynlint.py.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import sys
import threading
import time
import traceback
from typing import Callable, List, Optional

from .flight import FLIGHT_DIR_ENV, FlightRecorder, flight_recorder

logger = logging.getLogger(__name__)

# watchdogs register here so on-demand dumps (/debug/flight, SIGUSR2)
# can include every engine's probe/request-table/metrics in one artifact
_SOURCES: List["StallWatchdog"] = []


def _thread_stacks() -> List[dict]:
    """All-thread stacks via sys._current_frames, with thread names."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = []
    for ident, frame in sys._current_frames().items():
        stacks.append({
            "thread_id": ident,
            "name": names.get(ident, "?"),
            "stack": [
                line.rstrip("\n")
                for line in traceback.format_stack(frame)
            ],
        })
    return stacks


def build_flight_artifact(reason: str = "on_demand",
                          flight: Optional[FlightRecorder] = None,
                          ) -> dict:
    """Assemble one self-contained dump: ring + stacks + every
    registered watchdog's probe, request table, and metrics snapshot.

    Events merge across rings: the process-wide recorder plus any
    private ring a registered engine records into (tests, multi-engine
    processes), chronological, deduped by ring identity."""
    rings = {}
    if flight is not None:
        rings[id(flight)] = flight
    else:
        g = flight_recorder()
        rings[id(g)] = g
        for wd in list(_SOURCES):
            if wd.flight is not None:
                rings.setdefault(id(wd.flight), wd.flight)
    events = sorted(
        (e for r in rings.values() for e in r.snapshot()),
        key=lambda e: e["t"],
    )
    dropped = sum(r.dropped for r in rings.values())
    sources = []
    for wd in list(_SOURCES):
        entry: dict = {"name": wd.name}
        try:
            entry["probe"] = wd.probe() if wd.probe is not None else None
            entry["requests"] = (
                wd.requests() if wd.requests is not None else None
            )
            entry["metrics"] = (
                wd.registry.render() if wd.registry is not None else None
            )
            entry["last_trip"] = wd.last_trip
        except Exception as e:
            # a dump must degrade, never fail: a half-torn-down engine
            # still contributes its name + the error
            logger.warning("flight source %s failed during dump: %s",
                           wd.name, e)
            entry["error"] = repr(e)
        sources.append(entry)
    # recent completed request traces (incl. their stitched remote span
    # sets): lets scripts/flightdump.py --trace render a request X-ray
    # offline from the artifact alone
    from . import tracing

    traces: List[dict] = []
    for rec in tracing.recorders():
        try:
            traces.extend(rec.recent(32))
        except Exception:
            logger.debug("trace recorder snapshot failed", exc_info=True)
    return {
        "version": 1,
        "reason": reason,
        "time": time.time(),
        "monotonic": time.monotonic(),
        "pid": os.getpid(),
        "events": events,
        "dropped_events": dropped,
        "threads": _thread_stacks(),
        "sources": sources,
        "traces": traces,
    }


def flight_dir() -> Optional[str]:
    return os.environ.get(FLIGHT_DIR_ENV) or None


def write_flight_artifact(artifact: dict,
                          out_dir: Optional[str] = None) -> Optional[str]:
    """Serialize one artifact to ``<dir>/flight-<pid>-<seq>-<reason>.json``.
    Blocking (disk IO) — async callers run it in an executor. Returns the
    path, or None when no dump dir is configured."""
    out_dir = out_dir or flight_dir()
    if not out_dir:
        return None
    os.makedirs(out_dir, exist_ok=True)
    # monotonic-ns suffix: two dumps in the same second never collide
    path = os.path.join(
        out_dir,
        f"flight-{os.getpid()}-{time.monotonic_ns()}"
        f"-{artifact.get('reason', 'dump')}.json",
    )
    with open(path, "w") as f:
        json.dump(artifact, f, default=str, indent=1)
    return path


_signal_installed = False


def install_signal_dump() -> bool:
    """SIGUSR2 → write a flight artifact to DYN_FLIGHT_DIR (or log it as
    a single JSON line when no dir is configured). Idempotent; main
    thread only (signal module restriction); returns whether installed.

    The handler spawns a short-lived thread for the dump so the signal
    context does only scheduling — and so a wedged event loop (the very
    situation that makes an operator reach for SIGUSR2) cannot block it.
    """
    global _signal_installed
    if _signal_installed:
        return True

    def _dump_in_thread(signum, frame):
        def work():
            try:
                artifact = build_flight_artifact(reason="sigusr2")
                path = write_flight_artifact(artifact)
                if path:
                    logger.warning("flight artifact dumped to %s", path)
                else:
                    logger.warning(
                        "flight artifact (no %s configured): %s",
                        FLIGHT_DIR_ENV, json.dumps(artifact, default=str),
                    )
            except Exception:
                logger.exception("SIGUSR2 flight dump failed")

        threading.Thread(target=work, name="flight-dump", daemon=True).start()

    try:
        signal.signal(signal.SIGUSR2, _dump_in_thread)
    except (ValueError, AttributeError, OSError) as e:
        # non-main thread, or a platform without SIGUSR2
        logger.debug("SIGUSR2 flight dump not installed: %s", e)
        return False
    _signal_installed = True
    return True


class StallWatchdog:
    """Samples one engine's liveness; dumps + counts on a trip.

    ``probe()`` returns the scheduler's liveness snapshot (see
    Scheduler.watchdog_probe): ``heartbeat_t`` (monotonic stamp of the
    last loop pass), ``steps`` (dispatch counter), ``queue_depth``,
    ``active`` (occupied slots), ``stopping``. ``requests()`` returns
    the active request table for the artifact.
    """

    def __init__(
        self,
        probe: Callable[[], dict],
        requests: Optional[Callable[[], list]] = None,
        registry=None,
        flight: Optional[FlightRecorder] = None,
        interval_s: float = 1.0,
        stall_s: float = 30.0,
        dump_dir: Optional[str] = None,
        name: str = "engine",
    ):
        self.probe = probe
        self.requests = requests
        self.flight = flight if flight is not None else flight_recorder()
        self.interval_s = max(0.02, interval_s)
        self.stall_s = max(self.interval_s, stall_s)
        self.dump_dir = dump_dir  # None → DYN_FLIGHT_DIR at dump time
        self.name = name
        self.registry = registry
        if registry is None:
            from .registry import MetricsRegistry

            self.registry = MetricsRegistry()
        self._trips = self.registry.counter(
            "dynamo_watchdog_trips_total",
            "Stall-watchdog trips, labelled reason="
            "decode_stall|no_throughput|event_loop_lag",
        )
        self._lag_gauge = self.registry.gauge(
            "dynamo_runtime_event_loop_lag_seconds",
            "Sampled asyncio event-loop lag: how late the watchdog's "
            "periodic sleep fired vs. its deadline",
        )
        self._task: Optional[asyncio.Task] = None
        # trip subscribers (recovery/controller.py): called with the trip
        # info dict AFTER the artifact is dumped — detection stays useful
        # even when the subscriber's recovery goes wrong
        self._trip_listeners: List[Callable[[dict], None]] = []
        # (steps value, monotonic time it last changed) for no_throughput
        self._steps_mark: Optional[tuple] = None
        # reasons currently tripped; re-arm only when the condition clears
        self._tripped: set = set()
        self.trips: List[dict] = []  # public record for tests/inspection
        self.last_trip: Optional[dict] = None
        self.loop_lag_s = 0.0

    # ---------- lifecycle ----------

    def start(self) -> "StallWatchdog":
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name=f"watchdog-{self.name}")
            _SOURCES.append(self)
        return self

    async def stop(self) -> None:
        task, self._task = self._task, None
        if self in _SOURCES:
            _SOURCES.remove(self)
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    # ---------- the loop ----------

    async def _run(self) -> None:
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(self.interval_s)
            self.loop_lag_s = max(
                0.0, time.monotonic() - t0 - self.interval_s)
            self._lag_gauge.set(self.loop_lag_s)
            try:
                await self._check(time.monotonic())
            except asyncio.CancelledError:
                raise
            except Exception:
                # the watchdog must outlive a flaky probe — log and keep
                # sampling (a dead watchdog is a silent failure mode of
                # its own)
                logger.exception("watchdog check failed; continuing")

    async def _check(self, now: float) -> None:
        snap = self.probe()
        if snap.get("stopping"):
            return
        hb = snap.get("heartbeat_t")
        heartbeat = now if hb is None else float(hb)
        depth = int(snap.get("queue_depth") or 0)
        active = int(snap.get("active") or 0)
        # remote-prefill and prefix-pull waits carry their own deadline
        # + local-fallback machinery, so they count toward "the loop
        # must be alive" (decode_stall — a wedged loop can't run either
        # fallback) but NOT toward "the loop must be dispatching"
        # (no_throughput) — a slow-but-healthy prefill worker or KV
        # transfer is not a starvation
        remote = (int(snap.get("pending_remote") or 0)
                  + int(snap.get("pending_pull") or 0))
        steps = snap.get("steps")

        # no_throughput bookkeeping: when did `steps` last advance? The
        # clock also re-stamps while the queue is empty — steps frozen
        # with nothing queued is rest, and without the reset the FIRST
        # sample after a long idle gap that sees new arrivals would read
        # the ancient mark and trip instantly
        if steps is not None:
            if (depth == 0 or self._steps_mark is None
                    or self._steps_mark[0] != steps):
                self._steps_mark = (steps, now)

        pending = depth > 0 or active > 0 or remote > 0
        stale = pending and (now - heartbeat) > self.stall_s
        starved = (
            depth > 0
            and self._steps_mark is not None
            and (now - self._steps_mark[1]) > self.stall_s
        )
        lagged = self.loop_lag_s > self.stall_s

        await self._edge("decode_stall", stale, snap, now - heartbeat)
        # a stale heartbeat already explains frozen steps — don't double-
        # report the same wedge under a second reason
        await self._edge("no_throughput", starved and not stale, snap,
                         now - self._steps_mark[1] if self._steps_mark
                         else 0.0)
        await self._edge("event_loop_lag", lagged, snap, self.loop_lag_s)

    async def _edge(self, reason: str, condition: bool, snap: dict,
                    stalled_for: float) -> None:
        """Edge-triggered trip: fire once when ``condition`` becomes
        true; re-arm when it clears."""
        if not condition:
            self._tripped.discard(reason)
            return
        if reason in self._tripped:
            return
        self._tripped.add(reason)
        await self.trip(reason, snap, stalled_for)

    async def trip(self, reason: str, snap: dict,
                   stalled_for: float) -> Optional[str]:
        self._trips.inc(reason=reason)
        self.flight.record(
            "watchdog.trip", reason=reason, name=self.name,
            stalled_for_s=round(stalled_for, 3), **{
                k: snap.get(k)
                for k in ("queue_depth", "active", "steps")
            },
        )
        info = {
            "reason": reason,
            "name": self.name,
            "time": time.time(),
            "stalled_for_s": stalled_for,
            "probe": dict(snap),
        }
        self.trips.append(info)
        self.last_trip = info
        loop = asyncio.get_running_loop()
        # artifact assembly walks scheduler state and renders metrics —
        # cheap, but the write is disk IO: both ride the executor so a
        # slow volume can't stall the loop we're supposed to be watching
        path = await loop.run_in_executor(None, self._dump, reason)
        info["artifact"] = path
        logger.error(
            "WATCHDOG TRIP [%s] %s: stalled for %.1fs "
            "(queue_depth=%s active=%s steps=%s)%s",
            self.name, reason, stalled_for, snap.get("queue_depth"),
            snap.get("active"), snap.get("steps"),
            f" — flight artifact at {path}" if path
            else f" — set {FLIGHT_DIR_ENV} to persist flight artifacts",
        )
        for fn in list(self._trip_listeners):
            try:
                fn(info)
            except Exception:
                # recovery must never take detection down with it
                logger.exception("watchdog trip listener failed")
        return path

    def add_trip_listener(self, fn: Callable[[dict], None]) -> None:
        """Subscribe to trips (sync callback; schedule your own task for
        anything long-running — the watchdog keeps sampling)."""
        self._trip_listeners.append(fn)

    def _dump(self, reason: str) -> Optional[str]:
        # no flight= argument: this watchdog is registered in _SOURCES,
        # so the artifact merges its ring WITH the process-wide one —
        # coordinator/transfer/router events record into the global ring
        # and must not vanish from trip dumps
        artifact = build_flight_artifact(reason=reason)
        return write_flight_artifact(artifact, self.dump_dir)
