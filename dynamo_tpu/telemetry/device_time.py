"""Live device-time accounting + serving-time roofline attribution.

``bench.py`` computes a roofline fraction OFFLINE (measured decode
tokens/s over the HBM-bandwidth bound for the same model/batch) and
banks it in BENCH_*.json; in serving, the engine was blind. This module
is the live mirror: the scheduler already observes every compiled
program's completion — the sync path's executor host-sync, the
dispatch-ahead pipeline's reconciliation, the persistent loop's
``is_ready`` row drain — so each observation feeds a
:class:`DeviceTimeTracker` that derives, with **zero added host syncs
on the hot path**:

- ``dynamo_engine_device_time_seconds{program,phase}`` — per-burst
  device-busy durations (histogram: the ``_sum`` is cumulative busy
  time, the buckets its distribution);
- ``dynamo_engine_device_busy_ratio{phase}`` — busy vs. bubble over a
  rolling window (1.0 = the device never waited for the host);
- ``dynamo_engine_roofline_fraction`` — achieved HBM bytes/s over the
  chip's peak for the decode phase: every decode step must stream the
  weights once plus each live row's KV context, so
  ``bytes = steps × (param_bytes + Σ ctx_i × kv_bytes_per_token)`` and
  ``fraction = (bytes / busy_s) / peak`` — the exact serving-time twin
  of bench.py's ``vs_baseline``.

Busy time uses a serialized-interval estimator: the device executes its
queue in order, so for observations arriving in completion order the
busy contribution of one program is ``ready − max(dispatch,
previous_ready)`` and the gap ``dispatch − previous_ready`` (when
positive) is a bubble — the device genuinely ran dry. Under chained
dispatch the intervals overlap and the estimator correctly collapses
them instead of double-counting.

Measurement points are the host's EXISTING synchronization seams; the
only approximation is that a ready time is observed when the host
reconciles (is_ready probe or executor sync), which can trail the true
device completion by the drain lag. That skews busy UP and bubbles DOWN
— conservative in the direction that matters (a reported bubble is
always real).
"""

from __future__ import annotations

import collections
import os
import time
from typing import Callable, Deque, Optional, Tuple

# single-chip HBM bandwidth bound used for the roofline denominator.
# v5e ≈ 819 GB/s (the same constant bench.py uses); override with
# DYN_HBM_GBPS for other chip generations.
HBM_GBPS_ENV = "DYN_HBM_GBPS"
DEFAULT_HBM_GBPS = 819.0

# device-time histogram ladder: bursts are sub-millisecond to ~seconds
DEVICE_TIME_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0,
)


class DeviceTimeTracker:
    """Per-program device-busy accounting + live roofline fraction.

    ``observe()`` is called at host reconciliation seams only — it does
    pure float arithmetic and registry updates, never a device sync.
    """

    def __init__(
        self,
        param_bytes: float = 0.0,
        kv_bytes_per_token: float = 0.0,
        hbm_gbps: Optional[float] = None,
        window_s: float = 60.0,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        from .registry import MetricsRegistry

        self.param_bytes = float(param_bytes)
        self.kv_bytes_per_token = float(kv_bytes_per_token)
        if hbm_gbps is None:
            try:
                hbm_gbps = float(os.environ.get(HBM_GBPS_ENV, "")
                                 or DEFAULT_HBM_GBPS)
            except ValueError:
                hbm_gbps = DEFAULT_HBM_GBPS
        self.peak_bytes_per_s = float(hbm_gbps) * 1e9
        self.window_s = window_s
        self.clock = clock
        self._last_ready_t: Optional[float] = None
        # rolling (t, phase, busy_s, bubble_s, bytes) samples for the
        # live gauges; lifetime totals back them up when traffic pauses
        self._window: Deque[Tuple[float, str, float, float, float]] = (
            collections.deque(maxlen=4096)
        )
        self.busy_s: dict = {}      # phase → lifetime busy seconds
        self.bubble_s: dict = {}    # phase → lifetime bubble seconds
        self.decode_bytes = 0.0     # lifetime decode HBM-read bytes
        self.decode_tokens = 0
        # lifetime byte-carrying prefill observations (the SP ladder's
        # byte model) — folded into the roofline beside decode bytes
        self.prefill_bytes = 0.0
        self.prefill_byte_busy_s = 0.0
        self.observations = 0

        # private registry by default; the scheduler attaches it so the
        # series render in the engine's scrape (CompileTracker idiom)
        self.registry = registry or MetricsRegistry()
        self._time_hist = self.registry.histogram(
            "dynamo_engine_device_time_seconds",
            "Per-dispatch device-busy duration at the host's "
            "reconciliation seams, labelled program= and phase="
            "prefill|decode (the _sum series is cumulative device time)",
            buckets=DEVICE_TIME_BUCKETS,
        )
        self.registry.callback_gauge(
            "dynamo_engine_device_busy_ratio",
            "Device busy / (busy + bubble) per phase over the rolling "
            "window — 1.0 means the device never waited for the host",
            self._busy_ratios,
        )
        self.registry.callback_gauge(
            "dynamo_engine_roofline_fraction",
            "Achieved decode HBM bytes/s over the chip's peak bandwidth "
            "(weights once + live rows' KV per step) — the serving-time "
            "mirror of bench.py's vs_baseline",
            self._roofline,
        )

    # ---------- observations (host reconciliation seams) ----------

    def decode_read_bytes(self, k_steps: int,
                          context_tokens: int) -> float:
        """HBM bytes one K-step decode burst must stream: the weights
        once per step plus the live rows' KV contexts
        (``context_tokens`` = Σ context lengths across the rows)."""
        return float(k_steps) * (
            self.param_bytes + context_tokens * self.kv_bytes_per_token
        )

    def sp_prefill_read_bytes(self, chunks: int, context_tokens: int,
                              kernel: bool = False) -> float:
        """HBM bytes one sequence-parallel prefill LADDER must stream
        (the scheduler observes the whole ladder at its single drain
        seam, whose busy window covers every queued chunk): the weights
        once per chunk, each chunk's committed prefix (triangular sum
        ≈ ctx·(chunks−1)/2 tokens), and the full context's KV written
        once. ``kernel`` selects the paged-DMA route's prefix traffic
        (ops/pallas_sp.py streams cache pages straight into the online
        softmax — one pass per prefix token); the XLA gather route
        (default) pays three: the cache read, the materialized
        [W·bs]-token gather write, and its re-read by attention."""
        prefix = context_tokens * max(0, chunks - 1) / 2.0
        passes = 1.0 if kernel else 3.0
        return float(chunks) * self.param_bytes + (
            self.kv_bytes_per_token * (passes * prefix + context_tokens)
        )

    def observe(self, program: str, phase: str, dispatch_t: float,
                ready_t: float, read_bytes: float = 0.0,
                tokens: int = 0) -> float:
        """One program completion: dispatch and host-observed ready
        times (monotonic). Returns the busy seconds attributed."""
        last = self._last_ready_t
        start = dispatch_t if last is None else max(dispatch_t, last)
        busy = max(0.0, ready_t - start)
        bubble = max(0.0, start - last) if last is not None else 0.0
        self._last_ready_t = max(ready_t, last or ready_t)
        self.observations += 1
        self.busy_s[phase] = self.busy_s.get(phase, 0.0) + busy
        if bubble:
            self.bubble_s[phase] = self.bubble_s.get(phase, 0.0) + bubble
        if phase == "decode":
            self.decode_bytes += read_bytes
            self.decode_tokens += tokens
        elif program == "prefill_sp" and read_bytes:
            # the SP ladder's modelled bytes feed the roofline beside
            # decode — real HBM traffic either way. Other prefill
            # observations stay out even if a caller passes bytes: only
            # programs with an explicit byte model may shape the gauge.
            self.prefill_bytes += read_bytes
            self.prefill_byte_busy_s += busy
        self._time_hist.observe(busy, program=program, phase=phase)
        byte_sample = (
            read_bytes
            if (phase == "decode" or program == "prefill_sp") else 0.0
        )
        self._window.append((self.clock(), phase, busy, bubble,
                             byte_sample))
        return busy

    def idle(self) -> None:
        """The device ran out of work entirely (request-starved idle):
        reset the serialization point so the wait for the NEXT request
        is never charged as a bubble — matching the scheduler's own
        bubble-clock reset when it sleeps."""
        self._last_ready_t = None

    # ---------- live gauges ----------

    def _samples(self):
        cutoff = self.clock() - self.window_s
        # list() first: this renders off-loop while the reconciliation
        # seams append — iterating the live deque during an append
        # raises "deque mutated during iteration"
        return [s for s in list(self._window) if s[0] >= cutoff]

    # registry render callbacks — run wherever /metrics renders
    # dynrace: domain(executor)
    def _busy_ratios(self):
        samples = self._samples()
        agg: dict = {}
        for _, phase, busy, bubble, _b in samples:
            b, g = agg.get(phase, (0.0, 0.0))
            agg[phase] = (b + busy, g + bubble)
        out = []
        for phase, (busy, bubble) in sorted(agg.items()):
            if busy + bubble > 0:
                out.append(({"phase": phase}, busy / (busy + bubble)))
        return out

    # dynrace: domain(executor)
    def _roofline(self):
        if not self.peak_bytes_per_s:
            return []
        # every byte-carrying observation counts: decode steps always
        # model their reads; prefill observations carry bytes only when
        # the SP ladder modelled them (dense-ladder prefill stays out —
        # its bytes are unmodelled, so counting its busy time would
        # deflate the fraction)
        samples = [s for s in self._samples()
                   if s[1] == "decode" or s[4] > 0]
        busy = sum(s[2] for s in samples)
        read = sum(s[4] for s in samples)
        if busy <= 0 or read <= 0:
            # nothing inside the window: fall back to lifetime totals
            # so a scrape just after a burst of traffic isn't blind
            busy = (self.busy_s.get("decode", 0.0)
                    + self.prefill_byte_busy_s)
            read = self.decode_bytes + self.prefill_bytes
        if busy <= 0 or read <= 0:
            return []
        return [({}, (read / busy) / self.peak_bytes_per_s)]
