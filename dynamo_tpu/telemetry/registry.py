"""Process-wide metrics registry + the Prometheus instrument primitives.

No client lib in the environment — the text exposition format is simple
enough to emit directly. The instrument classes started life in
``http/metrics.py`` (reference analog: lib/llm/src/http/service/
metrics.rs:37-130); they live here now so every layer — HTTP service,
scheduler, block allocator, KV router, disagg coordinator — registers
into the same exposition instead of keeping private counters only a
scrape RPC could see.

Naming convention (enforced by scripts/check_metric_names.py):
``dynamo_<component>_<name>_<unit>`` — e.g.
``dynamo_scheduler_step_duration_seconds``,
``dynamo_kv_evictions_total``. Counters end in ``_total``.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Tuple

DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# scheduler steps are millisecond-scale; the request-level ladder above
# would collapse them into its two lowest buckets
STEP_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5)


def escape_label_value(value: str) -> str:
    """Prometheus text-format label escaping: backslash, double quote, and
    newline must be escaped or the exposition line is unparseable (model
    names and error strings routinely contain all three)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def format_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _escape_help(text: str) -> str:
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


class Counter:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self.values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        self.values[key] = self.values.get(key, 0.0) + amount

    def set_sample(self, value: float, **labels: str) -> None:
        """Overwrite a series with a scraped snapshot of a remote
        monotonic counter (the federation pattern) — NOT for first-party
        counting, which must go through ``inc``."""
        self.values[tuple(sorted(labels.items()))] = value

    def _type(self) -> str:
        return "counter"

    def render(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} {self._type()}",
        ]
        for key, val in sorted(self.values.items()):
            lines.append(f"{self.name}{format_labels(dict(key))} {val}")
        return lines


class Gauge(Counter):
    def set(self, value: float, **labels: str) -> None:
        self.values[tuple(sorted(labels.items()))] = value

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def _type(self) -> str:
        return "gauge"


class Histogram:
    def __init__(self, name: str, help_: str, buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = buckets
        self.counts: Dict[Tuple[Tuple[str, str], ...], List[int]] = {}
        self.sums: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self.totals: Dict[Tuple[Tuple[str, str], ...], int] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        if key not in self.counts:
            self.counts[key] = [0] * len(self.buckets)
            self.sums[key] = 0.0
            self.totals[key] = 0
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[key][i] += 1
        self.sums[key] += value
        self.totals[key] += 1

    def render(self) -> List[str]:
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} histogram",
        ]
        for key in sorted(self.counts):
            labels = dict(key)
            for i, b in enumerate(self.buckets):
                lines.append(
                    f"{self.name}_bucket{format_labels({**labels, 'le': str(b)})} {self.counts[key][i]}"
                )
            lines.append(
                f"{self.name}_bucket{format_labels({**labels, 'le': '+Inf'})} {self.totals[key]}"
            )
            lines.append(f"{self.name}_sum{format_labels(labels)} {self.sums[key]}")
            lines.append(f"{self.name}_count{format_labels(labels)} {self.totals[key]}")
        return lines


class CallbackGauge:
    """A gauge whose value(s) come from a callback at render time.

    The callback may return a plain number (one unlabelled sample) or an
    iterable of ``(labels_dict, value)`` pairs (one sample per label set —
    e.g. per-worker router gauges). A broken or non-numeric callback
    renders nothing; /metrics must never go down with a component.
    """

    def __init__(self, name: str, help_: str, fn: Callable):
        self.name = name
        self.help = help_
        self.fn = fn

    def render(self) -> List[str]:
        try:
            value = self.fn()
            samples: List[Tuple[Dict[str, str], float]] = []
            if isinstance(value, bool):
                return []
            if isinstance(value, (int, float)):
                samples = [({}, float(value))]
            else:
                for labels, v in value:
                    if isinstance(v, bool) or not isinstance(v, (int, float)):
                        continue
                    samples.append((dict(labels), float(v)))
        # dynlint: allow(silent-except) - a broken callback must not take /metrics down
        except Exception:
            return []
        if not samples:
            return []
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} gauge",
        ]
        for labels, v in samples:
            lines.append(f"{self.name}{format_labels(labels)} {v}")
        return lines


class CallbackGauges:
    """Dict-returning callback → one unlabelled gauge per numeric key.

    The escape hatch for metrics whose NAMES are dynamic (BYO python-file
    engines return arbitrary dicts); first-party components should prefer
    named instruments, which the name lint can check.
    """

    def __init__(self, prefix: str, fn: Callable):
        self.prefix = prefix
        self.fn = fn

    def render(self) -> List[str]:
        lines: List[str] = []
        try:
            vals = self.fn() or {}
            if not isinstance(vals, dict):
                return []  # BYO engines may return anything
            for k, v in sorted(vals.items()):
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                name = f"{self.prefix}_{k}"
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {float(v)}")
        # dynlint: allow(silent-except) - a broken engine must not take /metrics down
        except Exception:
            return []
        return lines


class MetricsRegistry:
    """One exposition surface shared by every component of a process.

    Components get-or-create named instruments (``counter``/``gauge``/
    ``histogram``/``callback_gauge``); a component that already owns a
    registry (e.g. the disagg coordinator built before the scheduler)
    is ``attach``-ed so its instruments render into the same scrape.
    """

    def __init__(self) -> None:
        self._metrics: Dict[str, object] = {}
        self._order: List[object] = []
        self._children: List["MetricsRegistry"] = []

    # ---------- instrument creation ----------

    def _get_or_create(self, name: str, cls, *args):
        existing = self._metrics.get(name)
        if existing is not None:
            if not isinstance(existing, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(existing).__name__}, not {cls.__name__}"
                )
            return existing
        metric = cls(name, *args)
        self._metrics[name] = metric
        self._order.append(metric)
        return metric

    def counter(self, name: str, help_: str) -> Counter:
        return self._get_or_create(name, Counter, help_)

    def gauge(self, name: str, help_: str) -> Gauge:
        return self._get_or_create(name, Gauge, help_)

    def histogram(self, name: str, help_: str, buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, Histogram, help_, buckets)

    def callback_gauge(self, name: str, help_: str, fn: Callable) -> CallbackGauge:
        existing = self._metrics.get(name)
        if isinstance(existing, CallbackGauge):
            existing.fn = fn  # re-bind (e.g. engine restart)
            return existing
        return self._get_or_create(name, CallbackGauge, help_, fn)

    # ---------- composition ----------

    def register(self, metric) -> None:
        """Register a pre-built instrument (anything with ``render()``)."""
        name = getattr(metric, "name", None)
        if name is not None:
            self._metrics[name] = metric
        self._order.append(metric)

    def register_callback_gauges(self, prefix: str, fn: Callable) -> None:
        """Dict-returning callback → ``{prefix}_{key}`` gauges, pulled
        fresh at every render (BYO engines; dynamic names)."""
        self._order.append(CallbackGauges(prefix, fn))

    def attach(self, child: "MetricsRegistry") -> None:
        """Render ``child``'s instruments as part of this exposition."""
        if child is self or child in self._children:
            return
        self._children.append(child)

    # ---------- output ----------

    def names(self) -> List[str]:
        out = list(self._metrics)
        for child in self._children:
            out.extend(child.names())
        return out

    def render_lines(self) -> List[str]:
        lines: List[str] = []
        for metric in self._order:
            lines.extend(metric.render())
        for child in self._children:
            lines.extend(child.render_lines())
        return lines

    def render(self) -> str:
        return "\n".join(self.render_lines()) + "\n"
