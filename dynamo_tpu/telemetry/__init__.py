"""Unified engine telemetry: one metrics registry + per-request traces.

The observability layer every component registers into (reference analogs:
lib/llm/src/http/service/metrics.rs for the HTTP instrument set,
ForwardPassMetrics for worker scrapes, and the pipeline Context's stage
list for per-request latency breakdowns). The HTTP frontend renders ONE
Prometheus exposition from a :class:`MetricsRegistry` that the scheduler,
block allocator, KV router, and disagg coordinator all feed; per-request
spans ride :class:`~dynamo_tpu.runtime.engine.AsyncEngineContext` and are
queryable at ``GET /debug/requests/{id}``.
"""

from .flight import CompileTracker, FlightRecorder, flight_recorder
from .history import LocalHistorySampler, MetricHistory
from .hub import FleetHub
from .incidents import IncidentConfig, IncidentRecorder
from .registry import (
    DEFAULT_BUCKETS,
    CallbackGauge,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    escape_label_value,
    format_labels,
)
from .tracing import TraceRecorder, span_breakdown
from .watchdog import StallWatchdog, build_flight_artifact

__all__ = [
    "DEFAULT_BUCKETS",
    "CallbackGauge",
    "CompileTracker",
    "Counter",
    "FleetHub",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "IncidentConfig",
    "IncidentRecorder",
    "LocalHistorySampler",
    "MetricHistory",
    "MetricsRegistry",
    "StallWatchdog",
    "TraceRecorder",
    "build_flight_artifact",
    "escape_label_value",
    "flight_recorder",
    "format_labels",
    "span_breakdown",
]
