"""In-engine flight recorder + XLA compile observability.

The black box the aggregate gauges can't be: when a serving worker
stalls or dies, ``/metrics`` says *that* throughput went flat, not *why*.
The :class:`FlightRecorder` is a process-wide bounded ring of structured
engine events — scheduler admission/preemption/dispatch/drain/rollback,
allocator eviction/OOM, disagg commit/nack/poison/local-fallback, KV
router picks, XLA compiles — each stamped with monotonic time and the
request/trace id it belongs to. The ring is cheap enough to run always
(one dict build + deque append per event, no locks on the append path)
and bounded (default 4096 events, oldest evicted, evictions counted), so
the last N seconds of engine decisions are ALWAYS reconstructable — the
stall watchdog (telemetry/watchdog.py), ``GET /debug/flight``, and
SIGUSR2 all dump it.

The :class:`CompileTracker` is the recompile-storm detector: on TPU a
request shape missing the bucket ladder triggers a multi-ten-second XLA
compile on the hot path (docs/perf_tuning.md warns; nothing detected
it). Every compiled-program entry point in ``engine/model_runner.py``
runs through ``track(program, key)``: the first dispatch of a distinct
(program, shape-bucket) key is a compile — its wall time is recorded,
it lands in the flight ring, and it increments
``dynamo_engine_xla_compiles_total{program,phase}`` where phase is
``startup`` before ``mark_serving_started()`` and ``late`` after. A
nonzero late-compile rate IS the storm signal (warmup should have swept
every serving shape).
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import threading
import time
from contextlib import contextmanager, nullcontext
from typing import List, Optional

logger = logging.getLogger(__name__)

FLIGHT_DIR_ENV = "DYN_FLIGHT_DIR"
FLIGHT_EVENTS_ENV = "DYN_FLIGHT_EVENTS"
DEFAULT_CAPACITY = 4096


class FlightRecorder:
    """Bounded ring of structured engine events.

    Append is O(1) and lock-free on CPython (``deque.append`` with a
    ``maxlen`` is atomic under the GIL; the monotonic ``appended``
    counter makes the eviction count derivable without coordination), so
    recording from the scheduler loop, executor threads (compile
    tracking during warmup), and transfer callbacks never contends.
    ``snapshot()`` is the only reader and copies the ring atomically.
    """

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(FLIGHT_EVENTS_ENV, "")
                               or DEFAULT_CAPACITY)
            except ValueError:
                capacity = DEFAULT_CAPACITY
        self.capacity = max(16, capacity)
        self._ring: "collections.deque" = collections.deque(
            maxlen=self.capacity)
        self._seq = itertools.count()
        self.appended = 0  # lifetime events; dropped = appended - len(ring)

    @property
    def dropped(self) -> int:
        """Events evicted by the ring bound (oldest-first, like
        TraceRecorder's drop-and-count — except here the NEWEST survive:
        a flight recorder's job is the moments before the crash)."""
        return max(0, self.appended - len(self._ring))

    def record(self, kind: str, request_id: Optional[str] = None,
               trace_id: Optional[str] = None, **data) -> None:
        """Append one event. Never raises, never blocks, never touches
        the event loop — safe from any thread, any layer."""
        evt = {
            "seq": next(self._seq),
            "t": time.monotonic(),
            "wall": time.time(),
            "kind": kind,
        }
        if request_id is not None:
            evt["request_id"] = request_id
        if trace_id is not None and trace_id != request_id:
            evt["trace_id"] = trace_id
        if data:
            evt["data"] = data
        self.appended += 1
        self._ring.append(evt)

    def snapshot(self, request_id: Optional[str] = None,
                 n: Optional[int] = None) -> List[dict]:
        """Chronological copy of the ring, optionally filtered to one
        request id and/or capped to the most recent ``n``."""
        events = list(self._ring)  # atomic under the GIL
        if request_id is not None:
            events = [
                e for e in events
                if e.get("request_id") == request_id
                or e.get("trace_id") == request_id
            ]
        if n is not None:
            events = events[-n:]
        return events

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


# the process-wide recorder every component records into by default;
# tests inject private recorders instead of resetting this one
_GLOBAL = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return _GLOBAL


class CompileTracker:
    """Detects and times XLA/Mosaic compiles at the dispatch seam.

    jit compiles happen synchronously inside the first call with a new
    static shape, so the first dispatch of a distinct (program,
    shape-bucket key) IS the compile and its wall time is dominated by
    it. The tracker keeps the seen-key set (one lock, held only for the
    membership test — warmup runs in an executor thread while serving
    dispatches from the loop) and classifies each compile by phase:
    ``startup`` until ``mark_serving_started()``, ``late`` after. Late
    compiles are the recompile-storm signal and additionally log a
    warning with the offending shape key.
    """

    def __init__(self, flight: Optional[FlightRecorder] = None,
                 registry=None):
        from .registry import MetricsRegistry

        self.flight = flight if flight is not None else flight_recorder()
        # private registry by default; the scheduler / prefill worker
        # attach it so the compile series render in the engine's scrape
        self.registry = registry or MetricsRegistry()
        self._compiles = self.registry.counter(
            "dynamo_engine_xla_compiles_total",
            "Compiled-program builds, labelled program= and phase="
            "startup|late (late = after serving started: the "
            "recompile-storm signal — warmup should have swept every "
            "serving shape)",
        )
        self._duration = self.registry.histogram(
            "dynamo_engine_xla_compile_duration_seconds",
            "Wall time of each program compile (first dispatch of a "
            "distinct shape-bucket key), labelled program=",
        )
        self._lock = threading.Lock()
        self._seen: set = set()
        self._serving = False
        self.records: List[dict] = []  # every compile, for tests/debug
        self.late_compiles = 0
        # optional per-dispatch context hook (program name → context
        # manager): the engine installs ops.attention.route_program so
        # trace-time route records carry the program label
        self.dispatch_cm = None

    def mark_serving_started(self) -> None:
        """Compiles from now on are ``late`` — the engine is serving, so
        every further compile stalls a real request."""
        self._serving = True

    def reset_seen(self) -> None:
        """Forget every seen key: the runner rebuilt its jitted programs
        (e.g. the warmup Pallas→XLA fallback), so the next dispatch per
        shape compiles again and must count again."""
        with self._lock:
            self._seen.clear()

    @property
    def serving(self) -> bool:
        return self._serving

    @contextmanager
    def track(self, program: str, key: str):
        """Wrap ONE dispatch of ``program`` at shape-bucket ``key``;
        records a compile iff this (program, key) was never dispatched."""
        hook = self.dispatch_cm
        with hook(program) if hook is not None else nullcontext():
            with self._lock:
                first = (program, key) not in self._seen
                if first:
                    self._seen.add((program, key))
            if not first:
                yield False
                return
            yield from self._track_first(program, key)

    def _track_first(self, program: str, key: str):
        t0 = time.monotonic()
        try:
            yield True
        finally:
            dt = time.monotonic() - t0
            phase = "late" if self._serving else "startup"
            self._compiles.inc(program=program, phase=phase)
            self._duration.observe(dt, program=program)
            self.records.append({
                "program": program, "key": key, "phase": phase,
                "duration_s": dt,
            })
            self.flight.record(
                "xla.compile", program=program, key=key, phase=phase,
                duration_s=round(dt, 4),
            )
            if phase == "late":
                self.late_compiles += 1
                logger.warning(
                    "late XLA compile: program=%s key=%s took %.2fs on "
                    "the serving path — a request shape missed the "
                    "bucket ladder (see docs/perf_tuning.md)",
                    program, key, dt,
                )
