"""Mini Prometheus text-format parser.

Just enough of the exposition grammar to validate our own /metrics
output (tests/test_telemetry.py) and to let tooling diff scrapes:
``# TYPE``/``# HELP`` headers, samples with escaped label values, and
histogram family suffixes. Not a general scraper — one metric per line,
no exemplars, no OpenMetrics extensions.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class Sample:
    name: str
    labels: Dict[str, str]
    value: float


@dataclasses.dataclass
class MetricFamily:
    name: str
    type: str = "untyped"
    help: Optional[str] = None
    samples: List[Sample] = dataclasses.field(default_factory=list)


def _unescape(value: str) -> str:
    out: List[str] = []
    i = 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ("\\", '"'):
                out.append(nxt)
            else:
                out.append(c)
                out.append(nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_labels(body: str) -> Dict[str, str]:
    """``a="x",b="y"`` → dict, honoring escapes inside quoted values."""
    labels: Dict[str, str] = {}
    i = 0
    while i < len(body):
        eq = body.index("=", i)
        key = body[i:eq].strip().lstrip(",").strip()
        if body[eq + 1] != '"':
            raise ValueError(f"unquoted label value at {body[eq:]!r}")
        j = eq + 2
        raw: List[str] = []
        while True:
            if j >= len(body):
                raise ValueError(f"unterminated label value in {body!r}")
            c = body[j]
            if c == "\\":
                raw.append(body[j:j + 2])
                j += 2
                continue
            if c == '"':
                break
            raw.append(c)
            j += 1
        labels[key] = _unescape("".join(raw))
        i = j + 1
    return labels


def parse_sample_line(line: str) -> Sample:
    if "{" in line:
        name, rest = line.split("{", 1)
        body, value_part = rest.rsplit("}", 1)
        labels = _parse_labels(body)
    else:
        name, value_part = line.split(None, 1)
        labels = {}
    value_str = value_part.strip()
    if value_str == "+Inf":
        value = math.inf
    elif value_str == "-Inf":
        value = -math.inf
    else:
        value = float(value_str)
    return Sample(name.strip(), labels, value)


def base_family(sample_name: str) -> str:
    """Histogram/summary suffixes map to their family name."""
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            return sample_name[: -len(suffix)]
    return sample_name


def parse_exposition(text: str) -> Dict[str, MetricFamily]:
    """Exposition text → family name → MetricFamily.

    Samples attach to the family declared by ``# TYPE`` when one exists
    (so histogram ``_bucket``/``_sum``/``_count`` group together);
    headerless samples get an untyped family of their own name.
    """
    families: Dict[str, MetricFamily] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            fam = families.setdefault(name, MetricFamily(name))
            fam.help = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, type_text = rest.partition(" ")
            fam = families.setdefault(name, MetricFamily(name))
            fam.type = type_text.strip()
            continue
        if line.startswith("#"):
            continue
        sample = parse_sample_line(line)
        fam_name = base_family(sample.name)
        if fam_name not in families and sample.name in families:
            fam_name = sample.name  # e.g. a gauge literally named *_count
        fam = families.setdefault(fam_name, MetricFamily(fam_name))
        fam.samples.append(sample)
    return families


def histogram_series(
    family: MetricFamily,
) -> Dict[Tuple[Tuple[str, str], ...], dict]:
    """Group a histogram family's samples per label set (minus ``le``).

    Returns label-key → {"buckets": [(le, cum_count)...sorted], "sum": x,
    "count": n} for validity checks (bucket monotonicity, +Inf == count).
    """
    series: Dict[Tuple[Tuple[str, str], ...], dict] = {}
    for s in family.samples:
        labels = dict(s.labels)
        le = labels.pop("le", None)
        key = tuple(sorted(labels.items()))
        entry = series.setdefault(key, {"buckets": [], "sum": None, "count": None})
        if s.name.endswith("_bucket"):
            bound = math.inf if le == "+Inf" else float(le)
            entry["buckets"].append((bound, s.value))
        elif s.name.endswith("_sum"):
            entry["sum"] = s.value
        elif s.name.endswith("_count"):
            entry["count"] = s.value
    for entry in series.values():
        entry["buckets"].sort(key=lambda b: b[0])
    return series
