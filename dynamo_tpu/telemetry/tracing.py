"""Per-request trace spans: recorder + breakdown rendering.

A request's trace id is assigned at ingress (the HTTP frontend honors
``X-Request-Id``) and travels on the request's
:class:`~dynamo_tpu.runtime.engine.AsyncEngineContext` — the same object
the scheduler stamps stages onto (``admission`` → ``prefill`` →
``first_token`` → ``completion``) and whose id rides the runtime
messaging envelope so disaggregated remote-prefill hops carry context.

Completed traces land in a bounded ring buffer, queryable at
``GET /debug/requests/{id}``, and are optionally appended as JSONL to the
file named by ``DYN_TRACE_JSONL`` (one object per request — the
machine-shippable sibling of ``DYN_LOGGING_JSONL``).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

TRACE_JSONL_ENV = "DYN_TRACE_JSONL"


def span_breakdown(stages: List[Tuple[str, float]],
                   end: Optional[float] = None) -> List[dict]:
    """[(name, t_monotonic)] → spans with offsets and durations.

    Each stage's duration runs to the NEXT stage (the last one to ``end``,
    defaulting to now) — the structured twin of
    ``utils.logging.stage_summary``.
    """
    if not stages:
        return []
    t0 = stages[0][1]
    closed = list(stages) + [("", end if end is not None else time.monotonic())]
    return [
        {
            "name": name,
            "offset_s": round(t - t0, 6),
            "duration_s": round(max(0.0, t_next - t), 6),
        }
        for (name, t), (_, t_next) in zip(closed, closed[1:])
    ]


class TraceRecorder:
    """Bounded ring of completed request traces (+ optional JSONL sink)."""

    def __init__(self, capacity: int = 512,
                 jsonl_path: Optional[str] = None):
        self.capacity = capacity
        self.jsonl_path = (
            jsonl_path if jsonl_path is not None
            else os.environ.get(TRACE_JSONL_ENV) or None
        )
        # one persistent line-buffered handle — record() runs on the event
        # loop, so a per-request open()/close() would stall every
        # concurrent request on a slow disk
        self._sink = None
        self._traces: "collections.OrderedDict[str, dict]" = collections.OrderedDict()

    def record(
        self,
        request_id: str,
        model: str,
        status: str,
        stages: List[Tuple[str, float]],
        end: Optional[float] = None,
    ) -> dict:
        end = end if end is not None else time.monotonic()
        spans = span_breakdown(stages, end)
        trace = {
            "request_id": request_id,
            "model": model,
            "status": status,
            "time": time.time(),
            "total_s": round(end - stages[0][1], 6) if stages else 0.0,
            "spans": spans,
        }
        self._traces[request_id] = trace  # a reused id replaces its trace
        self._traces.move_to_end(request_id)
        while len(self._traces) > self.capacity:
            self._traces.popitem(last=False)
        if self.jsonl_path:
            try:
                if self._sink is None:
                    self._sink = open(self.jsonl_path, "a", buffering=1)
                self._sink.write(json.dumps(trace, ensure_ascii=False) + "\n")
            except (OSError, ValueError):
                logger.warning("trace JSONL write to %s failed",
                               self.jsonl_path, exc_info=True)
        return trace

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()
            self._sink = None

    def get(self, request_id: str) -> Optional[dict]:
        return self._traces.get(request_id)

    def recent(self, n: int = 50) -> List[dict]:
        return list(self._traces.values())[-n:]

    def __len__(self) -> int:
        return len(self._traces)
