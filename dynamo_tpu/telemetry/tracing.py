"""Per-request trace spans: recorder + breakdown rendering.

A request's trace id is assigned at ingress (the HTTP frontend honors
``X-Request-Id``) and travels on the request's
:class:`~dynamo_tpu.runtime.engine.AsyncEngineContext` — the same object
the scheduler stamps stages onto (``admission`` → ``prefill`` →
``first_token`` → ``completion``) and whose id rides the runtime
messaging envelope so disaggregated remote-prefill hops carry context.

Completed traces land in a bounded ring buffer, queryable at
``GET /debug/requests/{id}``, and are optionally appended as JSONL to the
file named by ``DYN_TRACE_JSONL`` (one object per request — the
machine-shippable sibling of ``DYN_LOGGING_JSONL``).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import queue
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

TRACE_JSONL_ENV = "DYN_TRACE_JSONL"
TRACE_TTL_ENV = "DYN_TRACE_TTL_S"
TRACE_CAPACITY_ENV = "DYN_TRACE_CAPACITY"
DEFAULT_TTL_S = 600.0
DEFAULT_CAPACITY = 512

# live recorders, for the flight artifact's traces section (watchdog.
# build_flight_artifact) — weak so a torn-down service never pins one
_RECORDERS: "weakref.WeakSet" = weakref.WeakSet()


def recorders() -> List["TraceRecorder"]:
    return list(_RECORDERS)


def span_breakdown(stages: List[Tuple[str, float]],
                   end: Optional[float] = None) -> List[dict]:
    """[(name, t_monotonic)] → spans with offsets and durations.

    Span ``X`` is the time from the PREVIOUS mark to the moment ``X``
    was stamped — marks record phase completions (the scheduler stamps
    ``prefill`` when prefill finishes), so attributing each gap to its
    closing mark is what makes "prefill took 41ms" land under
    ``prefill`` rather than under whatever mark happened to precede it.
    The first mark anchors t=0; the tail from the last mark to ``end``
    (default: now) is reported as ``egress``. The structured twin of
    ``utils.logging.stage_summary``.
    """
    if not stages:
        return []
    t0 = stages[0][1]
    closed = list(stages) + [("egress", end if end is not None else time.monotonic())]
    return [
        {
            "name": name_next,
            "offset_s": round(t - t0, 6),
            "duration_s": round(max(0.0, t_next - t), 6),
        }
        for (_, t), (name_next, t_next) in zip(closed, closed[1:])
    ]


class TraceRecorder:
    """Bounded ring of completed request traces (+ optional JSONL sink).

    Retention is bounded TWO ways so million-user traffic cannot grow
    trace memory without limit: ``capacity`` is a max-entries LRU bound
    (oldest completed trace evicted first) and ``ttl_s`` expires traces
    by age regardless of traffic (0 disables). Both are knobs
    (``--trace-capacity`` / ``--trace-ttl-s``, or the DYN_TRACE_* env
    vars) and every eviction counts on
    ``dynamo_trace_evicted_total{reason=capacity|ttl}``.
    """

    def __init__(self, capacity: Optional[int] = None,
                 jsonl_path: Optional[str] = None,
                 jsonl_queue_size: int = 1024,
                 ttl_s: Optional[float] = None,
                 registry=None,
                 clock: Callable[[], float] = time.monotonic):
        if capacity is None:
            try:
                capacity = int(os.environ.get(TRACE_CAPACITY_ENV, "")
                               or DEFAULT_CAPACITY)
            except ValueError:
                capacity = DEFAULT_CAPACITY
        if ttl_s is None:
            try:
                ttl_s = float(os.environ.get(TRACE_TTL_ENV, "")
                              or DEFAULT_TTL_S)
            except ValueError:
                ttl_s = DEFAULT_TTL_S
        self.capacity = max(1, capacity)
        self.ttl_s = max(0.0, ttl_s)
        self.clock = clock
        self._ingest_t: Dict[str, float] = {}  # request id → ingest time
        # store mutations lock: record() runs on the event loop, but
        # get()/recent() prune too and are called from watchdog/executor
        # threads (flight-artifact assembly) — an unlocked prune racing
        # a record could evict a just-written trace or KeyError mid-pop
        self._store_lock = threading.Lock()
        self.evicted = 0  # lifetime evictions (both reasons)
        self._evicted_c = None
        if registry is not None:
            self.register_into(registry)
        _RECORDERS.add(self)
        self.jsonl_path = (
            jsonl_path if jsonl_path is not None
            else os.environ.get(TRACE_JSONL_ENV) or None
        )
        # record() runs on the event loop (HttpService calls it per
        # request), so ALL sink IO — the open included — happens on a
        # dedicated single writer thread behind a BOUNDED queue: FIFO
        # ordering is preserved, a slow (network) filesystem can't stall
        # concurrent requests, and a HUNG one can't grow memory without
        # bound — excess traces are dropped and counted instead
        self._sink = None
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, jsonl_queue_size))
        self._writer: Optional[threading.Thread] = None
        self._stop = threading.Event()  # close() signal; survives a full queue
        self._abandoned = False  # close() gave up: the writer owns the sink
        self.dropped = 0  # traces not written because the queue was full
        self._traces: "collections.OrderedDict[str, dict]" = collections.OrderedDict()

    def register_into(self, registry) -> None:
        """Register the eviction counter + store gauge into a
        MetricsRegistry (the HTTP service attaches its own)."""
        self._evicted_c = registry.counter(
            "dynamo_trace_evicted_total",
            "Completed traces evicted from the debug store, by reason="
            "capacity (max-entries LRU) | ttl (age bound)",
        )
        registry.callback_gauge(
            "dynamo_trace_store_requests",
            "Completed traces currently held in the debug store",
            # dynrace: domain(executor)
            lambda: len(self._traces),
        )

    def _evict(self, reason: str, n: int = 1) -> None:
        self.evicted += n
        if self._evicted_c is not None:
            self._evicted_c.inc(n, reason=reason)

    def _prune(self, now: Optional[float] = None) -> None:
        """TTL + capacity enforcement (lazy: on record and on reads).
        Callers hold ``_store_lock``."""
        now = self.clock() if now is None else now
        if self.ttl_s:
            cutoff = now - self.ttl_s
            expired = 0
            # insertion order == recency order: stop at the first fresh
            for rid in list(self._traces):
                if self._ingest_t.get(rid, now) > cutoff:
                    break
                self._traces.pop(rid, None)
                self._ingest_t.pop(rid, None)
                expired += 1
            if expired:
                self._evict("ttl", expired)
        while len(self._traces) > self.capacity:
            rid, _ = self._traces.popitem(last=False)
            self._ingest_t.pop(rid, None)
            self._evict("capacity")

    def _sink_write(self, line: str) -> None:
        try:
            if self._sink is None:
                self._sink = open(self.jsonl_path, "a", buffering=1)
            self._sink.write(line)
        except (OSError, ValueError):
            logger.warning("trace JSONL write to %s failed",
                           self.jsonl_path, exc_info=True)

    def _drain(self) -> None:
        try:
            while True:
                try:
                    line = self._queue.get(timeout=1.0)
                except queue.Empty:
                    # the stop flag (not just the sentinel) ends the loop:
                    # a sentinel can fail to enqueue into a full queue, and
                    # a writer that later recovers must still terminate
                    if self._stop.is_set():
                        return
                    continue
                if line is None:  # close() sentinel
                    return
                self._sink_write(line)
        finally:
            if self._abandoned and self._sink is not None:
                # close() already returned without the sink — it's ours now
                self._sink.close()
                self._sink = None

    def record(
        self,
        request_id: str,
        model: str,
        status: str,
        stages: List[Tuple[str, float]],
        end: Optional[float] = None,
        ctx=None,
    ) -> dict:
        """Record one completed request. ``ctx`` (the request's
        AsyncEngineContext, optional) contributes the cross-process
        pieces: the wall anchor of the first mark (``t0_wall``) and any
        remote span sets collected from downstream hops — what
        ``GET /debug/trace/{id}`` stitches into one timeline."""
        end = end if end is not None else time.monotonic()
        spans = span_breakdown(stages, end)
        trace = {
            "request_id": request_id,
            "model": model,
            "status": status,
            "time": time.time(),
            "total_s": round(end - stages[0][1], 6) if stages else 0.0,
            "spans": spans,
        }
        if ctx is not None and stages:
            trace["t0_wall"] = ctx.wall(stages[0][1])
            if ctx.remote_spans:
                trace["remote"] = list(ctx.remote_spans)
        with self._store_lock:
            self._traces[request_id] = trace  # a reused id replaces its trace
            self._traces.move_to_end(request_id)
            self._ingest_t[request_id] = self.clock()
            self._prune()
        if self.jsonl_path and not self._stop.is_set():  # no sink after close()
            if self._writer is None:
                self._writer = threading.Thread(
                    target=self._drain, name="trace-jsonl", daemon=True)
                self._writer.start()
            try:
                self._queue.put_nowait(
                    json.dumps(trace, ensure_ascii=False) + "\n")
            except queue.Full:
                self.dropped += 1
                if self.dropped == 1 or self.dropped % 1000 == 0:
                    logger.warning(
                        "trace JSONL sink backed up (%d dropped so far) — "
                        "is %s hung?", self.dropped, self.jsonl_path)
        return trace

    def close(self, timeout: float = 5.0) -> None:
        """Drain queued writes (bounded by ``timeout``) and close the sink.
        A writer wedged on a hung filesystem is abandoned — it's a daemon
        thread — rather than hanging shutdown forever."""
        writer, self._writer = self._writer, None
        if writer is not None:
            deadline = time.monotonic() + timeout
            self._stop.set()
            try:
                # bounded put sharing the overall budget: a backlogged-but-
                # healthy writer frees a slot for the sentinel; a wedged
                # one exhausts the deadline and is abandoned below
                self._queue.put(None, timeout=timeout)
            except queue.Full:
                pass
            writer.join(max(0.0, deadline - time.monotonic()))
            if writer.is_alive():
                # the stop flag guarantees the writer terminates (and
                # closes the sink itself) if the filesystem ever recovers
                self._abandoned = True
                if writer.is_alive():
                    logger.warning(
                        "trace JSONL writer did not drain within %.1fs "
                        "(%d queued, %d dropped); abandoning it — the "
                        "daemon thread finishes the backlog and exits if "
                        "the sink recovers",
                        timeout, self._queue.qsize(), self.dropped)
                    return  # the abandoned writer owns the sink now
                # it exited in the race window after join() — reclaim
                self._abandoned = False
        if self._sink is not None:
            self._sink.close()
            self._sink = None

    def get(self, request_id: str) -> Optional[dict]:
        with self._store_lock:
            self._prune()
            return self._traces.get(request_id)

    def recent(self, n: int = 50) -> List[dict]:
        with self._store_lock:
            self._prune()
            return list(self._traces.values())[-n:]

    def __len__(self) -> int:
        return len(self._traces)
