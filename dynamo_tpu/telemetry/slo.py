"""SLO attainment + goodput accounting at the HTTP edge.

Queue depths and slot occupancy are proxies; what the user experiences
is TTFT and inter-token latency. This module measures BOTH per request
at the edge (http/metrics.py stamps first-token and per-token times as
the chunks stream out) against configurable targets
(``--slo-ttft-ms`` / ``--slo-itl-ms``) and exports:

- ``dynamo_slo_attainment_total{slo=ttft|itl, met=true|false}`` — per-
  request attainment counters (ITL is judged on the request's WORST
  inter-token gap: one visible stall breaks the stream's feel, however
  good the mean looks);
- ``dynamo_slo_goodput_tokens_total`` — tokens produced by requests
  that met every configured target. ``rate()`` of this series is
  goodput: SLO-met tokens/s, the number a capacity plan should optimize
  instead of raw throughput;
- ``dynamo_slo_target_seconds{slo}`` — the configured targets, so
  dashboards label themselves.

``snapshot()`` is a planner signal source (planner/planner.py
``slo_source``): rolling-window attainment fractions + goodput rate
land in the SignalStore under the ``slo.*`` names policy.py consults —
the control loop can shed/scale on user-visible latency instead of
queue proxies.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Deque, Optional, Tuple


class SloTracker:
    """Per-request SLO verdicts + rolling attainment for the planner.

    ``ttft_s`` / ``itl_s``: targets in seconds; ``None`` leaves that
    dimension unjudged (a request meets it trivially). Construct with at
    least one target — the CLI only builds a tracker when an SLO flag is
    set.
    """

    def __init__(
        self,
        ttft_s: Optional[float] = None,
        itl_s: Optional[float] = None,
        window_s: float = 60.0,
        registry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        from .registry import MetricsRegistry

        self.ttft_s = ttft_s
        self.itl_s = itl_s
        self.window_s = window_s
        self.clock = clock
        self._created_t = clock()
        # rolling (t, ttft_ok, itl_ok, met, tokens) request verdicts
        self._window: Deque[Tuple[float, bool, bool, bool, int]] = (
            collections.deque(maxlen=4096)
        )
        self.requests = 0
        self.met_requests = 0
        self.goodput_tokens = 0

        self.registry = registry or MetricsRegistry()
        self._attain = self.registry.counter(
            "dynamo_slo_attainment_total",
            "Per-request SLO verdicts at the HTTP edge, labelled "
            "slo=ttft|itl and met=true|false (ITL judged on the worst "
            "inter-token gap of the stream)",
        )
        self._goodput = self.registry.counter(
            "dynamo_slo_goodput_tokens_total",
            "Tokens produced by requests that met every configured SLO "
            "— rate() of this series is goodput (SLO-met tokens/s)",
        )
        target = self.registry.gauge(
            "dynamo_slo_target_seconds",
            "Configured SLO targets, labelled slo=ttft|itl",
        )
        if ttft_s is not None:
            target.set(float(ttft_s), slo="ttft")
        if itl_s is not None:
            target.set(float(itl_s), slo="itl")

    # ---------- per-request verdicts ----------

    def observe(self, ttft_s: Optional[float], itl_max_s: Optional[float],
                tokens: int) -> bool:
        """One completed request: edge-measured TTFT, worst inter-token
        gap (None when the stream had < 2 tokens), and token count.
        Returns whether every configured target was met."""
        ttft_ok = (
            self.ttft_s is None
            or (ttft_s is not None and ttft_s <= self.ttft_s)
        )
        itl_ok = (
            self.itl_s is None
            or itl_max_s is None          # single-token: no gaps to judge
            or itl_max_s <= self.itl_s
        )
        judged = False
        if self.ttft_s is not None:
            self._attain.inc(slo="ttft", met="true" if ttft_ok else "false")
            judged = True
        if self.itl_s is not None and itl_max_s is not None:
            self._attain.inc(slo="itl", met="true" if itl_ok else "false")
            judged = True
        met = ttft_ok and itl_ok
        if judged:
            # the per-request conjunction, scrapeable: a remote consumer
            # (the fleet hub) can't recover "met EVERY configured SLO"
            # from the per-dimension series — blending dimensions
            # overstates attainment exactly when one dimension misses
            self._attain.inc(slo="request", met="true" if met else "false")
        self.requests += 1
        if met:
            self.met_requests += 1
            self.goodput_tokens += tokens
            self._goodput.inc(tokens)
        self._window.append((self.clock(), ttft_ok, itl_ok, met, tokens))
        # drop verdicts that have aged out of the window now, while the
        # deque head is cheap to test — snapshot()/window_count() scans
        # then touch only live rows instead of up to 4096 stale ones
        cutoff = self.clock() - self.window_s
        while self._window and self._window[0][0] < cutoff:
            self._window.popleft()
        return met

    def window_count(self) -> int:
        """Completed-request verdicts currently inside the window (the
        incident recorder's SLO probe gates on this so a 1-request blip
        can't read as a fleet incident)."""
        cutoff = self.clock() - self.window_s
        return sum(1 for r in self._window if r[0] >= cutoff)

    # ---------- planner signal source ----------

    def snapshot(self) -> dict:
        """Rolling-window SLO signals for the planner's SignalStore
        (names match planner/policy.py's SIG_SLO_* vocabulary). Empty
        when no request completed inside the window — the policy skips
        a blind signal instead of acting on a stale one."""
        now = self.clock()
        rows = [r for r in self._window if r[0] >= now - self.window_s]
        if not rows:
            return {}
        n = len(rows)
        # goodput rate over the OBSERVATION SPAN, not the gap since the
        # oldest surviving sample: a single request completing 1 ms
        # before the poll must read as tokens-over-elapsed-serving-time,
        # never tokens-over-1ms (a 300k tok/s spike into the planner)
        span = max(min(now - self._created_t, self.window_s), 1e-9)
        if (len(self._window) == self._window.maxlen
                and self._window[0][0] > now - self.window_s):
            # capacity eviction truncated the window: in-window verdicts
            # older than the retained 4096 are gone, so dividing their
            # tokens' absence by the FULL window span would underreport
            # goodput (3x at ~200 req/s). The retained rows cover only
            # [oldest, now] — the rate over that span is the measured
            # truth, and with the deque full it's never a 1-sample spike
            span = max(now - self._window[0][0], 1e-9)
        out = {
            "slo.attainment": sum(1 for r in rows if r[3]) / n,
            "slo.goodput_tokens_per_s": (
                sum(r[4] for r in rows if r[3]) / span
            ),
        }
        if self.ttft_s is not None:
            out["slo.ttft_attainment"] = sum(1 for r in rows if r[1]) / n
        if self.itl_s is not None:
            out["slo.itl_attainment"] = sum(1 for r in rows if r[2]) / n
        return out
