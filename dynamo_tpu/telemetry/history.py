"""Metric history rings: bounded (t, value) series with rate queries.

A Prometheus server keeps history; this repo's registries keep only the
CURRENT value of every instrument — so the moment something goes wrong,
"what did KV usage look like over the last two minutes" is unanswerable
from inside the process, and an incident bundle captured at trip time
(telemetry/incidents.py) would carry a single point instead of a curve.
The :class:`MetricHistory` closes that gap: a bounded dict of per-series
rings — ``(name, sorted-labels)`` → deque of ``(t, value)`` — that a
scraper (telemetry/hub.py, one ring set per remote worker) or a local
sampler (:class:`LocalHistorySampler`, the process's own registry on a
cadence) appends into.

Counter semantics are first-class: a scraped counter that goes BACKWARD
means the remote process restarted, not that work un-happened. Each
series detects the reset, counts it, and accumulates a monotonic offset
so ``rate()``/``delta()`` stay correct across restarts instead of going
hugely negative for one window (the classic naive-scraper artifact).

Bounds are structural, like the flight ring's: ``max_samples`` per
series (oldest evicted), ``window_s`` age pruning, and ``max_series``
total — a cardinality explosion on a scraped worker drops NEW series
(counted on ``dropped_series``) rather than growing host memory.

Threading: writers (``observe``/``ingest``) run on the event loop only;
readers may run anywhere — the /fleet handlers ride the executor, and
``registry.render`` (which invokes the hub's callback gauges over these
rings) runs executor-side in both the sidecar server and the hub's
local scrape. Reads therefore never mutate and take GIL-atomic
``list()`` snapshots of the dict/deques before iterating, so a
concurrent loop-side insert/append can't raise mid-iteration.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Deque, Dict, Iterable, List, Optional, Tuple

from .exposition import MetricFamily, base_family

LabelKey = Tuple[Tuple[str, str], ...]
SeriesKey = Tuple[str, LabelKey]

# exposition types treated as cumulative (reset-detected, rate-able);
# histogram _sum/_count samples are cumulative too and land as counters
_COUNTER_TYPES = ("counter", "histogram")


def label_key(labels: Optional[Dict[str, str]]) -> LabelKey:
    return tuple(sorted((labels or {}).items()))


class Series:
    """One bounded ring of (t, adjusted_value) samples.

    For counters ``adjusted`` is raw + the accumulated pre-reset offset,
    so the stored curve is monotonic across remote restarts and
    ``delta``/``rate`` never see a negative step.
    """

    __slots__ = ("kind", "points", "resets", "_offset", "_last_raw")

    def __init__(self, kind: str, max_samples: int):
        self.kind = kind  # "gauge" | "counter"
        self.points: Deque[Tuple[float, float]] = collections.deque(
            maxlen=max_samples)
        self.resets = 0
        self._offset = 0.0
        self._last_raw: Optional[float] = None

    def observe(self, t: float, raw: float) -> None:
        if self.kind == "counter":
            if self._last_raw is not None and raw < self._last_raw:
                # remote process restarted: fold the pre-reset total into
                # the offset so the adjusted curve keeps its monotonicity
                self.resets += 1
                self._offset += self._last_raw
            self._last_raw = raw
            raw = raw + self._offset
        self.points.append((t, raw))

    def prune(self, cutoff: float) -> None:
        while self.points and self.points[0][0] < cutoff:
            self.points.popleft()

    def latest(self) -> Optional[float]:
        try:
            return self.points[-1][1]  # deque[-1] is GIL-atomic
        except IndexError:
            return None

    def latest_in_window(self, cutoff: float) -> Optional[float]:
        """Newest value, or None when the series has aged past
        ``cutoff``. Non-mutating (off-loop safe) — the writer's
        ``observe`` does the real pruning."""
        try:
            t, v = self.points[-1]
        except IndexError:
            return None
        return v if t >= cutoff else None

    def delta(self, since: float) -> float:
        """adjusted(newest) - adjusted(oldest sample at/after ``since``);
        0.0 with fewer than two in-window samples."""
        window = [(t, v) for (t, v) in list(self.points) if t >= since]
        if len(window) < 2:
            return 0.0
        return window[-1][1] - window[0][1]

    def rate(self, since: float) -> float:
        """Per-second rate over the in-window samples (0.0 when the
        window holds fewer than two or spans no time)."""
        window = [(t, v) for (t, v) in list(self.points) if t >= since]
        if len(window) < 2:
            return 0.0
        dt = window[-1][0] - window[0][0]
        if dt <= 0:
            return 0.0
        return (window[-1][1] - window[0][1]) / dt


class MetricHistory:
    """Bounded per-series history rings + window queries.

    One instance per scraped worker (the hub) or per process (the local
    sampler feeding incident bundles). All methods are synchronous and
    lock-free: writers (``observe``/``ingest``) run on the event loop
    ONLY; readers never mutate and snapshot before iterating, so they
    are safe from executor threads too (the /fleet handlers, callback
    gauges invoked by an executor-side ``registry.render``).
    """

    def __init__(
        self,
        window_s: float = 600.0,
        max_samples: int = 512,
        max_series: int = 4096,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.window_s = window_s
        self.max_samples = max(2, max_samples)
        self.max_series = max(1, max_series)
        self.clock = clock
        self._series: Dict[SeriesKey, Series] = {}
        self.dropped_series = 0  # series refused by the max_series bound

    # ---------- writing ----------

    def observe(self, name: str, labels: Optional[Dict[str, str]],
                value: float, t: Optional[float] = None,
                kind: str = "gauge") -> None:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            return
        t = self.clock() if t is None else t
        key = (name, label_key(labels))
        series = self._series.get(key)
        if series is None:
            if len(self._series) >= self.max_series:
                self.dropped_series += 1
                return
            series = self._series[key] = Series(kind, self.max_samples)
        series.observe(t, float(value))
        series.prune(t - self.window_s)

    def ingest(self, families: Dict[str, MetricFamily],
               t: Optional[float] = None) -> None:
        """One parsed exposition (telemetry/exposition.py) → the rings.

        Histogram ``_bucket`` samples are skipped — per-``le`` series
        are the cardinality explosion the bounds exist to prevent, and
        ``_sum``/``_count`` carry everything rate queries need.
        """
        t = self.clock() if t is None else t
        for fam in families.values():
            kind = "counter" if fam.type in _COUNTER_TYPES else "gauge"
            for s in fam.samples:
                if s.name.endswith("_bucket"):
                    continue
                sample_kind = kind
                if fam.type == "histogram" and not (
                        s.name.endswith("_sum") or s.name.endswith("_count")):
                    sample_kind = "gauge"  # stray sample in a histogram family
                self.observe(s.name, s.labels, s.value, t=t,
                             kind=sample_kind)

    # ---------- reading ----------

    def series_count(self) -> int:
        return len(self._series)

    def names(self) -> List[str]:
        return sorted({name for (name, _) in list(self._series)})

    def kind(self, name: str) -> Optional[str]:
        """``"counter"`` if any series of ``name`` is cumulative,
        ``"gauge"`` otherwise, ``None`` for an unknown name."""
        kinds = {s.kind for _, s in self._match(name, None)}
        if not kinds:
            return None
        return "counter" if "counter" in kinds else "gauge"

    def name_summaries(self, window_s: Optional[float] = None,
                       prefix: str = "") -> Dict[str, dict]:
        """Single-pass per-name rollup over in-window series:
        ``{name: {"latest": label-set sum, "kind": counter-if-any,
        "rate": summed per-second rate (counter series only)}}``.

        The hub's ``GET /fleet/metrics`` walks every name of every
        worker on dynamotop's poll cadence — per-name ``latest``/
        ``kind``/``rate`` calls would each rescan the whole series dict,
        going quadratic in series count. Off-loop safe like every
        reader."""
        now = self.clock()
        cutoff = now - self.window_s
        since = now - (window_s if window_s is not None else self.window_s)
        out: Dict[str, dict] = {}
        for (name, _), series in list(self._series.items()):
            if prefix and not name.startswith(prefix):
                continue
            v = series.latest_in_window(cutoff)
            if v is None:
                continue
            entry = out.setdefault(
                name, {"latest": 0.0, "kind": series.kind, "rate": 0.0})
            entry["latest"] += v
            if series.kind == "counter":
                entry["kind"] = "counter"
                entry["rate"] += series.rate(since)
        return out

    def _match(self, name: str,
               labels: Optional[Dict[str, str]]) -> Iterable[Tuple[SeriesKey, Series]]:
        """Series of ``name`` whose labels are a superset of ``labels``.
        Iterates a GIL-atomic snapshot: safe against loop-side inserts
        when the caller runs off-loop."""
        want = (labels or {}).items()
        for key, series in list(self._series.items()):
            if key[0] != name:
                continue
            have = dict(key[1])
            if all(have.get(k) == v for k, v in want):
                yield key, series

    def samples(self, name: str,
                labels: Optional[Dict[str, str]] = None,
                ) -> List[Tuple[Dict[str, str], float]]:
        """Latest in-window value per matching label set."""
        cutoff = self.clock() - self.window_s
        out = []
        for key, series in self._match(name, labels):
            v = series.latest_in_window(cutoff)
            if v is not None:
                out.append((dict(key[1]), v))
        return out

    def latest(self, name: str, labels: Optional[Dict[str, str]] = None,
               default: Optional[float] = None) -> Optional[float]:
        """Newest in-window value summed across matching label sets
        (one series → its value; labelled counters → the family total)."""
        vals = [v for _, v in self.samples(name, labels)]
        if not vals:
            return default
        return sum(vals)

    def delta(self, name: str, labels: Optional[Dict[str, str]] = None,
              window_s: Optional[float] = None) -> float:
        since = self.clock() - (window_s if window_s is not None
                                else self.window_s)
        return sum(s.delta(since) for _, s in self._match(name, labels))

    def rate(self, name: str, labels: Optional[Dict[str, str]] = None,
             window_s: Optional[float] = None) -> float:
        since = self.clock() - (window_s if window_s is not None
                                else self.window_s)
        return sum(s.rate(since) for _, s in self._match(name, labels))

    def resets(self, name: str,
               labels: Optional[Dict[str, str]] = None) -> int:
        return sum(s.resets for _, s in self._match(name, labels))

    def window(self, name: str, labels: Optional[Dict[str, str]] = None,
               window_s: Optional[float] = None,
               ) -> List[Tuple[float, float]]:
        """Chronological in-window points, merged across matching label
        sets (single-series names — the common bundle/sparkline case)."""
        since = self.clock() - (window_s if window_s is not None
                                else self.window_s)
        pts: List[Tuple[float, float]] = []
        for _, series in self._match(name, labels):
            pts.extend(p for p in list(series.points) if p[0] >= since)
        pts.sort(key=lambda p: p[0])
        return pts

    def snapshot(self, window_s: Optional[float] = None,
                 names: Optional[Iterable[str]] = None) -> dict:
        """JSON-ready dump of every ring (the incident bundle's
        ``history.json``): per-series kind, labels, resets, and the
        in-window points with BOTH the monotonic t and a wall estimate
        so offline tooling can label the x axis."""
        window_s = self.window_s if window_s is None else window_s
        now = self.clock()
        wall_now = time.time()
        since = now - window_s
        keep = set(names) if names is not None else None
        series_out = []
        for (name, lk), series in sorted(list(self._series.items())):
            if keep is not None and name not in keep:
                continue
            pts = [(t, v) for (t, v) in list(series.points) if t >= since]
            if not pts:
                continue
            series_out.append({
                "name": name,
                "labels": dict(lk),
                "kind": series.kind,
                "resets": series.resets,
                "points": [
                    [round(t - now, 3), round(wall_now + (t - now), 3), v]
                    for (t, v) in pts
                ],
            })
        return {
            "window_s": window_s,
            "time": wall_now,
            "dropped_series": self.dropped_series,
            "series": series_out,
        }


class LocalHistorySampler:
    """Samples the process's OWN registry into a :class:`MetricHistory`.

    The in-process sibling of the hub's remote scrape: render → parse →
    ingest on a cadence, so the incident recorder always has the last
    few minutes of local metric history to bundle at trip time. Render
    and parse ride the executor (they walk every instrument), and the
    task is held and cancelled on ``stop()``.
    """

    def __init__(self, registry, history: Optional[MetricHistory] = None,
                 interval_s: float = 5.0,
                 window_s: float = 600.0):
        self.registry = registry
        self.history = history if history is not None else MetricHistory(
            window_s=window_s)
        self.interval_s = max(0.02, interval_s)
        self._task = None

    def start(self) -> "LocalHistorySampler":
        import asyncio

        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._run(), name="metric-history-sampler")
        return self

    async def stop(self) -> None:
        import asyncio

        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def sample_once(self) -> None:
        import asyncio

        from .exposition import parse_exposition

        loop = asyncio.get_running_loop()
        families = await loop.run_in_executor(
            None, lambda: parse_exposition(self.registry.render()))
        self.history.ingest(families)

    async def _run(self) -> None:
        import asyncio
        import logging

        log = logging.getLogger(__name__)
        while True:
            try:
                await self.sample_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # a broken instrument must not kill history collection —
                # the ring's whole job is being there when things break
                log.exception("metric history sample failed; continuing")
            await asyncio.sleep(self.interval_s)
