"""Server-sent-events codec (reference: lib/llm/src/protocols/codec.rs).

Encodes pydantic models / dicts as ``data: {json}\n\n`` lines with the
OpenAI ``data: [DONE]`` terminator, and parses them back (used by tests
and the batch client).
"""

from __future__ import annotations

import json
from typing import Any, Iterator, Optional

DONE = "[DONE]"


def encode_event(data: Any, event: Optional[str] = None, comment: Optional[str] = None) -> bytes:
    """One SSE frame. ``data`` may be a pydantic model, dict, or string."""
    lines = []
    if comment is not None:
        lines.append(f": {comment}")
    if event is not None:
        lines.append(f"event: {event}")
    if data is not None:
        if hasattr(data, "model_dump_json"):
            payload = data.model_dump_json(exclude_none=True)
        elif isinstance(data, str):
            payload = data
        else:
            payload = json.dumps(data, separators=(",", ":"))
        lines.append(f"data: {payload}")
    return ("\n".join(lines) + "\n\n").encode()


def encode_done() -> bytes:
    return encode_event(DONE)


def parse_stream(raw: bytes) -> Iterator[dict]:
    """Parse a full SSE byte stream into the JSON payloads (skips [DONE])."""
    for block in raw.decode().split("\n\n"):
        for line in block.splitlines():
            if line.startswith("data: "):
                payload = line[len("data: "):]
                if payload.strip() == DONE:
                    continue
                yield json.loads(payload)
