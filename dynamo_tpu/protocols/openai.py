"""OpenAI-compatible API types (chat completions + completions).

Pydantic models for the HTTP boundary, mirroring the surface the reference
wraps from async-openai (reference: lib/llm/src/protocols/openai/* — chat,
completions, nvext extension). The ``nvext`` extension field is kept
name-compatible so clients written against the reference work unchanged
(use_raw_prompt, annotations, ignore_eos).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Literal, Optional, Union

from pydantic import BaseModel, ConfigDict, Field, model_validator

from .common import FinishReason

def _reject_unsupported_extras(req: BaseModel) -> BaseModel:
    """Reject beam-search fields the engine does not honor. The reference
    carries use_beam_search/length_penalty in SamplingOptions as an engine
    pass-through (reference: lib/llm/src/protocols/common.rs:248-316); no
    TPU engine here implements beam search, so accepting them silently
    would change sampling semantics without telling the client."""
    extra = req.model_extra or {}
    # no-op values are allowed: clients built on vLLM-style SamplingParams
    # serialize their defaults (use_beam_search=false, length_penalty=1.0),
    # which request no beam search at all
    if extra.get("use_beam_search"):
        raise ValueError(
            "'use_beam_search' is not supported by this server (beam "
            "search is not implemented); remove it from the request"
        )
    lp = extra.get("length_penalty")
    if lp is not None and lp != 1.0:
        raise ValueError(
            "'length_penalty' is not supported by this server (beam "
            "search is not implemented); remove it from the request"
        )
    rf = getattr(req, "response_format", None)
    if rf and rf.get("type") not in (None, "text", "json_object",
                                     "json_schema"):
        raise ValueError(
            f"response_format type {rf.get('type')!r} is not supported; "
            "use 'json_object', 'json_schema', 'text', or the "
            "'guided_choice' extra field"
        )
    if rf and rf.get("type") == "json_schema":
        js = rf.get("json_schema")
        if not isinstance(js, dict) or not isinstance(
                js.get("schema"), dict):
            raise ValueError(
                "response_format json_schema requires "
                "{'json_schema': {'schema': {...}}} (OpenAI structured-"
                "outputs shape)"
            )
    return req


class NvExt(BaseModel):
    """Extension block: non-standard knobs (name-compatible with reference)."""

    model_config = ConfigDict(extra="allow")
    use_raw_prompt: Optional[bool] = None
    ignore_eos: Optional[bool] = None
    annotations: Optional[List[str]] = None
    greed_sampling: Optional[bool] = None


class ChatMessage(BaseModel):
    model_config = ConfigDict(extra="allow")
    role: str
    content: Optional[Union[str, List[Dict[str, Any]]]] = None
    name: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None
    tool_call_id: Optional[str] = None

    def text_content(self) -> str:
        if isinstance(self.content, str):
            return self.content
        if isinstance(self.content, list):
            return "".join(
                part.get("text", "") for part in self.content if part.get("type") == "text"
            )
        return ""


class StreamOptions(BaseModel):
    include_usage: Optional[bool] = None


class ChatCompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    messages: List[ChatMessage]
    max_tokens: Optional[int] = None
    max_completion_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None  # common extension
    min_p: Optional[float] = None
    n: Optional[int] = None
    stop: Optional[Union[str, List[str]]] = None
    stream: Optional[bool] = None
    stream_options: Optional[StreamOptions] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    seed: Optional[int] = None
    logprobs: Optional[bool] = None
    top_logprobs: Optional[int] = None
    logit_bias: Optional[Dict[str, float]] = None
    min_tokens: Optional[int] = None
    ignore_eos: Optional[bool] = None
    user: Optional[str] = None
    tools: Optional[List[Dict[str, Any]]] = None
    tool_choice: Optional[Union[str, Dict[str, Any]]] = None
    response_format: Optional[Dict[str, Any]] = None
    nvext: Optional[NvExt] = None

    _no_beam = model_validator(mode="after")(_reject_unsupported_extras)

    def effective_max_tokens(self) -> Optional[int]:
        # `is None`, not falsy: max_completion_tokens=0 means an empty
        # completion, same as the completions endpoint's max_tokens=0
        if self.max_completion_tokens is not None:
            return self.max_completion_tokens
        return self.max_tokens

    def stop_list(self) -> List[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)


class CompletionRequest(BaseModel):
    model_config = ConfigDict(extra="allow")
    model: str
    prompt: Union[str, List[str], List[int], List[List[int]]]
    max_tokens: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    min_p: Optional[float] = None
    n: Optional[int] = None
    stop: Optional[Union[str, List[str]]] = None
    stream: Optional[bool] = None
    stream_options: Optional[StreamOptions] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    seed: Optional[int] = None
    logprobs: Optional[int] = None
    logit_bias: Optional[Dict[str, float]] = None
    best_of: Optional[int] = None
    echo: Optional[bool] = None
    min_tokens: Optional[int] = None
    ignore_eos: Optional[bool] = None
    user: Optional[str] = None
    nvext: Optional[NvExt] = None

    _no_beam = model_validator(mode="after")(_reject_unsupported_extras)

    def stop_list(self) -> List[str]:
        if self.stop is None:
            return []
        return [self.stop] if isinstance(self.stop, str) else list(self.stop)


class Usage(BaseModel):
    prompt_tokens: int = 0
    completion_tokens: int = 0
    total_tokens: int = 0


class ChatChoiceDelta(BaseModel):
    role: Optional[str] = None
    content: Optional[str] = None
    tool_calls: Optional[List[Dict[str, Any]]] = None


class LogprobEntry(BaseModel):
    token: str
    logprob: float
    bytes: Optional[List[int]] = None
    top_logprobs: List[Dict[str, Any]] = Field(default_factory=list)


class ChoiceLogprobs(BaseModel):
    content: Optional[List[LogprobEntry]] = None


class ChatStreamChoice(BaseModel):
    index: int = 0
    delta: ChatChoiceDelta = Field(default_factory=ChatChoiceDelta)
    finish_reason: Optional[str] = None
    logprobs: Optional[ChoiceLogprobs] = None


class ChatCompletionChunk(BaseModel):
    id: str
    object: Literal["chat.completion.chunk"] = "chat.completion.chunk"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[ChatStreamChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class ChatChoice(BaseModel):
    index: int = 0
    message: ChatMessage
    finish_reason: Optional[str] = None
    logprobs: Optional[ChoiceLogprobs] = None


class ChatCompletionResponse(BaseModel):
    id: str
    object: Literal["chat.completion"] = "chat.completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[ChatChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class CompletionChoice(BaseModel):
    index: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    logprobs: Optional[Dict[str, Any]] = None


class CompletionResponse(BaseModel):
    id: str
    object: Literal["text_completion"] = "text_completion"
    created: int = Field(default_factory=lambda: int(time.time()))
    model: str = ""
    choices: List[CompletionChoice] = Field(default_factory=list)
    usage: Optional[Usage] = None


class ModelInfo(BaseModel):
    id: str
    object: Literal["model"] = "model"
    created: int = Field(default_factory=lambda: int(time.time()))
    owned_by: str = "dynamo-tpu"
    # dynamo extensions (reference http/service/openai.rs model metadata;
    # family/aliases come from the model registry's cards)
    max_model_len: Optional[int] = None
    model_type: Optional[str] = None
    family: Optional[str] = None
    aliases: Optional[List[str]] = None


class ModelList(BaseModel):
    object: Literal["list"] = "list"
    data: List[ModelInfo] = Field(default_factory=list)


def new_request_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex}"


def aggregate_chat_stream(
    chunks: List[ChatCompletionChunk],
) -> ChatCompletionResponse:
    """Fold a chunk stream into a full response (non-streaming requests).

    Reference analog: the stream→full aggregators in
    lib/llm/src/protocols/openai/chat_completions/aggregator.rs.
    """
    content: Dict[int, List[str]] = {}
    finish: Dict[int, Optional[str]] = {}
    logprobs: Dict[int, List[LogprobEntry]] = {}
    role: Dict[int, str] = {}
    tool_calls: Dict[int, List[Dict[str, Any]]] = {}
    usage: Optional[Usage] = None
    rid, model, created = "", "", int(time.time())
    for chunk in chunks:
        rid = chunk.id or rid
        model = chunk.model or model
        created = chunk.created
        if chunk.usage is not None:
            usage = chunk.usage
        for choice in chunk.choices:
            idx = choice.index
            if choice.delta.role:
                role[idx] = choice.delta.role
            if choice.delta.content:
                content.setdefault(idx, []).append(choice.delta.content)
            if choice.delta.tool_calls:
                # fold the streamed shape back into whole entries: a delta
                # carrying an "id" opens call slot "index"; id-less deltas
                # are argument fragments that concatenate into that slot
                # (the streamed tool-call contract chat_stream emits). The
                # stream "index" key itself never reaches the aggregate.
                merged = tool_calls.setdefault(idx, [])
                for c in choice.delta.tool_calls:
                    entry = {k: v for k, v in c.items() if k != "index"}
                    si = c.get("index")
                    if c.get("id") or si is None or si >= len(merged):
                        merged.append(entry)
                        continue
                    target = merged[si]
                    frag = (entry.get("function") or {})
                    fn = target.setdefault("function", {})
                    if frag.get("name"):
                        fn["name"] = fn.get("name", "") + frag["name"]
                    if frag.get("arguments"):
                        fn["arguments"] = (
                            fn.get("arguments", "") + frag["arguments"]
                        )
            if choice.finish_reason is not None:
                finish[idx] = choice.finish_reason
            if choice.logprobs and choice.logprobs.content:
                logprobs.setdefault(idx, []).extend(choice.logprobs.content)
    indices = sorted(set(content) | set(finish) | set(role) | set(tool_calls)) or [0]
    return ChatCompletionResponse(
        id=rid,
        model=model,
        created=created,
        choices=[
            ChatChoice(
                index=i,
                message=ChatMessage(
                    role=role.get(i, "assistant"),
                    content="".join(content.get(i, [])) or None
                    if i in tool_calls else "".join(content.get(i, [])),
                    tool_calls=tool_calls.get(i),
                ),
                finish_reason=finish.get(i),
                logprobs=ChoiceLogprobs(content=logprobs[i]) if i in logprobs else None,
            )
            for i in indices
        ],
        usage=usage,
    )


def aggregate_completion_stream(chunks: List[CompletionResponse]) -> CompletionResponse:
    text: Dict[int, List[str]] = {}
    finish: Dict[int, Optional[str]] = {}
    lps: Dict[int, dict] = {}
    textlen: Dict[int, int] = {}
    usage: Optional[Usage] = None
    rid, model, created = "", "", int(time.time())
    for chunk in chunks:
        rid = chunk.id or rid
        model = chunk.model or model
        created = chunk.created
        if chunk.usage is not None:
            usage = chunk.usage
        for choice in chunk.choices:
            if choice.logprobs:
                # merge legacy logprobs blocks; offsets rebase onto the
                # text accumulated BEFORE this chunk (the chunk's offsets
                # are relative to its own text)
                base = textlen.get(choice.index, 0)
                m = lps.setdefault(choice.index, {
                    "tokens": [], "token_logprobs": [],
                    "top_logprobs": None, "text_offset": [],
                })
                m["tokens"] += choice.logprobs.get("tokens", [])
                m["token_logprobs"] += choice.logprobs.get("token_logprobs", [])
                tops = choice.logprobs.get("top_logprobs")
                if tops:
                    m["top_logprobs"] = (m["top_logprobs"] or []) + tops
                m["text_offset"] += [
                    base + o for o in choice.logprobs.get("text_offset", [])
                ]
            if choice.text:
                textlen[choice.index] = (
                    textlen.get(choice.index, 0) + len(choice.text)
                )
                text.setdefault(choice.index, []).append(choice.text)
            if choice.finish_reason is not None:
                finish[choice.index] = choice.finish_reason
    indices = sorted(set(text) | set(finish)) or [0]
    return CompletionResponse(
        id=rid,
        model=model,
        created=created,
        choices=[
            CompletionChoice(
                index=i, text="".join(text.get(i, [])),
                finish_reason=finish.get(i), logprobs=lps.get(i),
            )
            for i in indices
        ],
        usage=usage,
    )
