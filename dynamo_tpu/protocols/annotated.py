"""Annotated stream envelope: data deltas + out-of-band events.

Reference analog: lib/runtime/src/protocols/annotated.rs:1-168 — every
service stream may interleave plain data items with named events
(annotations) and error markers; on the wire an annotation maps onto an
SSE frame with ``event:`` + ``:`` comment lines and no ``data:`` payload,
so OpenAI clients ignore it while instrumented clients (benchmarks,
debuggers) can read e.g. the preprocessor's ``formatted_prompt`` /
``token_ids`` annotations (preprocessor.rs:134-160).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, List, Optional

# annotation names the preprocessor understands (requested via
# nvext.annotations on the OpenAI request)
ANNOTATION_FORMATTED_PROMPT = "formatted_prompt"
ANNOTATION_TOKEN_IDS = "token_ids"


@dataclasses.dataclass
class Annotated:
    """One stream element: a data delta, an annotation event, or an error."""

    data: Optional[Any] = None
    id: Optional[str] = None
    event: Optional[str] = None
    comment: Optional[List[str]] = None

    @classmethod
    def from_error(cls, error: str) -> "Annotated":
        return cls(event="error", comment=[error])

    @classmethod
    def from_annotation(cls, name: str, value: Any) -> "Annotated":
        return cls(event=name, comment=[json.dumps(value)])

    @property
    def is_error(self) -> bool:
        return self.event == "error"

    @property
    def is_annotation(self) -> bool:
        return self.event is not None and self.event != "error"

    def annotation_value(self) -> Any:
        """Decode the JSON payload of an annotation event."""
        if not self.comment:
            return None
        return json.loads(self.comment[0])

    def to_wire(self) -> dict:
        """Dict form for the msgpack data plane (distributed graphs).

        Only event envelopes cross the wire — data deltas travel as their
        own raw chunks (``data`` is intentionally not serialized)."""
        body = {}
        for key in ("id", "event", "comment"):
            value = getattr(self, key)
            if value is not None:
                body[key] = value
        return {"__annotated__": body}

    @classmethod
    def maybe_from_wire(cls, obj: Any) -> Optional["Annotated"]:
        """Reconstruct from to_wire() output; None for anything else."""
        if isinstance(obj, cls):
            return obj
        if isinstance(obj, dict) and "__annotated__" in obj:
            return cls(**obj["__annotated__"])
        return None
