"""Internal engine-facing protocol: the framework's lingua franca.

Every request, after preprocessing, becomes a ``PreprocessedRequest`` of
token ids + sampling/stop options; every engine emits ``EngineOutput``
deltas of token ids. The HTTP protocol layer translates both ways.
Field semantics follow the reference's common protocol (reference:
lib/llm/src/protocols/common.rs:205-341 — StopConditions, SamplingOptions,
OutputOptions; common/llm_backend.rs — BackendInput/LLMEngineOutput),
re-designed as msgpack-friendly dataclasses.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional


class FinishReason(str, enum.Enum):
    EOS = "eos"          # hit the model's end-of-sequence token
    STOP = "stop"        # hit a user/model stop sequence or stop token id
    LENGTH = "length"    # hit max_tokens / context limit
    CANCELLED = "cancelled"
    ERROR = "error"

    def to_openai(self) -> str:
        if self in (FinishReason.EOS, FinishReason.STOP):
            return "stop"
        if self is FinishReason.LENGTH:
            return "length"
        return "stop" if self is FinishReason.CANCELLED else "error"


@dataclasses.dataclass
class StopConditions:
    max_tokens: Optional[int] = None
    min_tokens: Optional[int] = None
    stop: Optional[List[str]] = None                 # visible stop strings
    stop_token_ids_hidden: Optional[List[int]] = None  # never surfaced in text
    ignore_eos: bool = False
    # canonical tokenization of each ``stop`` string (preprocessor-
    # filled, aligned 1:1 with ``stop``): lets a token-level engine
    # detect stop strings without a tokenizer — the persistent decode
    # chain's device-approximate stop check hashes these. Text-level
    # matching across OTHER tokenizations stays the backend
    # detokenizer jail's job.
    stop_token_seqs: Optional[List[List[int]]] = None

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, d: dict) -> "StopConditions":
        return cls(**d)


@dataclasses.dataclass
class SamplingOptions:
    n: Optional[int] = None
    temperature: Optional[float] = None
    top_p: Optional[float] = None
    top_k: Optional[int] = None
    min_p: Optional[float] = None
    frequency_penalty: Optional[float] = None
    presence_penalty: Optional[float] = None
    repetition_penalty: Optional[float] = None
    seed: Optional[int] = None
    # OpenAI logit_bias: token id → additive logit offset (engine applies
    # it in the jitted sampler via a per-slot bias row)
    logit_bias: Optional[Dict[int, float]] = None
    # guided decoding (vLLM-style extra field): constrain the output to
    # one of these strings. The preprocessor tokenizes each choice; the
    # engine walks a token trie and masks the sampler's bias row per
    # step. Canonical-tokenization semantics: the output follows each
    # choice's whole-string tokenization.
    guided_choice: Optional[List[str]] = None
    # the trie's token ids (preprocessor-filled; engines consume this,
    # not the strings — the engine holds no tokenizer)
    guided_choice_token_ids: Optional[List[List[int]]] = None
    # guided JSON (OpenAI response_format / vLLM guided_json extra):
    # {"type": "json_object"} or {"type": "json_schema", "schema": {...}}.
    # The engine compiles it to a character-level JSON machine driving
    # the same per-step bias-row edits as guided_choice (engine/guided.py).
    guided_json: Optional[dict] = None

    def to_wire(self) -> dict:
        d = dataclasses.asdict(self)
        if d.get("logit_bias"):
            # string keys on the wire: msgpack's default strict_map_key
            # decoding (and JSON) reject int map keys
            d["logit_bias"] = {str(k): v for k, v in d["logit_bias"].items()}
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "SamplingOptions":
        if d.get("logit_bias"):
            d = {**d, "logit_bias": {
                int(k): float(v) for k, v in d["logit_bias"].items()
            }}
        return cls(**d)


@dataclasses.dataclass
class OutputOptions:
    logprobs: Optional[int] = None          # top-k logprobs per sampled token
    prompt_logprobs: Optional[int] = None
    skip_special_tokens: bool = True
    echo_prompt: bool = False

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, d: dict) -> "OutputOptions":
        return cls(**d)


@dataclasses.dataclass
class PreprocessedRequest:
    """Token-level request handed to an engine (or shipped to a worker)."""

    token_ids: List[int]
    stop_conditions: StopConditions = dataclasses.field(default_factory=StopConditions)
    sampling_options: SamplingOptions = dataclasses.field(default_factory=SamplingOptions)
    output_options: OutputOptions = dataclasses.field(default_factory=OutputOptions)
    eos_token_ids: List[int] = dataclasses.field(default_factory=list)
    model: Optional[str] = None
    mdc_checksum: Optional[str] = None
    annotations: List[str] = dataclasses.field(default_factory=list)
    # payloads answering requested annotations (formatted_prompt,
    # token_ids) — local side channel, deliberately NOT a wire field: the
    # preprocessor emits them as Annotated events before dispatch
    annotation_values: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_wire(self) -> dict:
        return {
            "token_ids": list(self.token_ids),
            "stop_conditions": self.stop_conditions.to_wire(),
            "sampling_options": self.sampling_options.to_wire(),
            "output_options": self.output_options.to_wire(),
            "eos_token_ids": list(self.eos_token_ids),
            "model": self.model,
            "mdc_checksum": self.mdc_checksum,
            "annotations": list(self.annotations),
        }

    @classmethod
    def from_wire(cls, d: dict) -> "PreprocessedRequest":
        return cls(
            token_ids=list(d["token_ids"]),
            stop_conditions=StopConditions.from_wire(d.get("stop_conditions", {})),
            sampling_options=SamplingOptions.from_wire(d.get("sampling_options", {})),
            output_options=OutputOptions.from_wire(d.get("output_options", {})),
            eos_token_ids=list(d.get("eos_token_ids", [])),
            model=d.get("model"),
            mdc_checksum=d.get("mdc_checksum"),
            annotations=list(d.get("annotations", [])),
        )


@dataclasses.dataclass
class TokenLogprob:
    token_id: int
    logprob: float
    top: Optional[Dict[int, float]] = None  # token_id -> logprob


@dataclasses.dataclass
class EngineOutput:
    """One streamed delta from an engine: newly generated token ids."""

    token_ids: List[int] = dataclasses.field(default_factory=list)
    finish_reason: Optional[FinishReason] = None
    logprobs: Optional[List[TokenLogprob]] = None
    # engine-side detokenized text, if the engine chooses to provide it
    text: Optional[str] = None
    # OutputOptions.prompt_logprobs result: one entry per prompt token
    # (first None — no conditioning prefix), sent once with the first
    # output (reference: lib/llm/src/protocols/common.rs:320-341)
    prompt_logprobs: Optional[List[Optional[float]]] = None
    # KV/scheduling telemetry piggybacked on outputs (optional)
    kv_transfer_params: Optional[dict] = None
    # migration control frame (recovery/migration.py): the request now
    # lives on a peer — ``{host, port, resume_id}`` lets the consumer
    # re-bind its stream directly to the peer so the source worker can
    # exit instead of staying up to relay. Carries no client payload.
    migrated: Optional[dict] = None
    # n>1 fan-out (engine/serving.py): which choice this delta belongs
    # to. None for single-choice requests — the overwhelmingly common
    # case pays no wire bytes.
    choice: Optional[int] = None

    def to_wire(self) -> dict:
        d: Dict[str, Any] = {"token_ids": list(self.token_ids)}
        if self.finish_reason is not None:
            d["finish_reason"] = self.finish_reason.value
        if self.choice is not None:
            d["choice"] = self.choice
        if self.text is not None:
            d["text"] = self.text
        if self.prompt_logprobs is not None:
            d["prompt_logprobs"] = self.prompt_logprobs
        if self.logprobs is not None:
            d["logprobs"] = [
                {
                    "token_id": lp.token_id,
                    "logprob": lp.logprob,
                    # string keys: int map keys fail msgpack's strict
                    # decode on the dial-back stream (and JSON)
                    "top": (
                        {str(k): v for k, v in lp.top.items()}
                        if lp.top else lp.top
                    ),
                }
                for lp in self.logprobs
            ]
        if self.kv_transfer_params is not None:
            d["kv_transfer_params"] = self.kv_transfer_params
        if self.migrated is not None:
            d["migrated"] = self.migrated
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "EngineOutput":
        fr = d.get("finish_reason")
        lps = d.get("logprobs")
        return cls(
            token_ids=list(d.get("token_ids", [])),
            finish_reason=FinishReason(fr) if fr else None,
            text=d.get("text"),
            logprobs=[
                TokenLogprob(
                    lp["token_id"], lp["logprob"],
                    {int(k): float(v) for k, v in lp["top"].items()}
                    if lp.get("top") else None,
                )
                for lp in lps
            ]
            if lps
            else None,
            prompt_logprobs=d.get("prompt_logprobs"),
            kv_transfer_params=d.get("kv_transfer_params"),
            migrated=d.get("migrated"),
            choice=d.get("choice"),
        )


@dataclasses.dataclass
class BackendOutput:
    """EngineOutput after the detokenizer stage: adds clean text deltas."""

    token_ids: List[int]
    text: Optional[str]
    finish_reason: Optional[FinishReason] = None
    logprobs: Optional[List[TokenLogprob]] = None
    prompt_logprobs: Optional[List[Optional[float]]] = None
    cum_tokens: int = 0
