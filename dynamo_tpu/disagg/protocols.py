"""Remote-prefill wire protocol + the prefill work queue.

Mirrors the reference's RemotePrefillRequest flow (reference:
examples/llm/components/worker.py:165-174 enqueue of block ids + engine id;
examples/llm/utils/nats_queue.py:27-155 JetStream work queue with one
consumer group) on top of the runtime's work-queue primitive, which gives
ack + visibility-timeout redelivery — a crashed prefill worker's items are
handed to another worker automatically (elastic recovery).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import msgpack


@dataclasses.dataclass
class RemotePrefillRequest:
    """A prompt whose KV should be computed remotely and pushed back.

    ``block_ids`` are the *decode worker's* cache slots covering the prompt;
    the prefill worker writes the suffix after ``num_cached`` tokens (the
    decode worker's local prefix-cache hit) into them via the transfer plane.
    """

    request_id: str
    engine_id: str            # decode engine that owns the blocks
    token_ids: List[int]
    block_ids: List[int]
    num_cached: int = 0       # decode-side prefix-hit tokens (block multiple)
    # sampling for the single prefill-sampled token (max_tokens=1 semantics)
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    min_p: float = 0.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    seed: Optional[int] = None
    want_logprobs: bool = False
    # alternatives count (OpenAI top_logprobs): 0 = chosen-token logprob
    # only — the prefill worker then skips the [B, V] top-k sort and
    # ships no top dict (matches the decode scheduler's logprobs_n gate)
    logprobs_n: int = 0
    logit_bias: Optional[dict] = None  # token id → additive logit offset
    # ingress-assigned correlation id (X-Request-Id); log/span context only —
    # transfer authorization and pending state key on request_id
    trace_id: str = ""
    # wall-clock (time.time) at enqueue, for the prefill worker's
    # queue-wait histogram; 0 = unset (older senders). Telemetry only —
    # never used for ordering or expiry (cross-process clock skew).
    enqueued_at: float = 0.0

    def to_wire(self) -> bytes:
        d = dataclasses.asdict(self)
        if d.get("logit_bias"):
            # string keys on the wire: msgpack's strict decode (queue pop)
            # rejects int map keys
            d["logit_bias"] = {str(k): v for k, v in d["logit_bias"].items()}
        return msgpack.packb(d, use_bin_type=True)

    @classmethod
    def from_wire(cls, data: bytes) -> "RemotePrefillRequest":
        d = msgpack.unpackb(data, raw=False)
        if d.get("logit_bias"):
            d["logit_bias"] = {
                int(k): float(v) for k, v in d["logit_bias"].items()
            }
        # drop unknown keys so the wire format stays forward-compatible:
        # a newer coordinator adding a field must not crash an older
        # worker's pop (mixed-version fleets during rolling upgrades)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


class PrefillQueue:
    """The shared prefill work queue, one per namespace.

    Decode workers push; prefill workers pop with a visibility window and
    ack only after the KV transfer has been committed, so worker death
    mid-prefill redelivers the item.
    """

    # Redelivery window. Kept >= the decode side's default prefill timeout
    # (RemotePrefillCoordinator.prefill_timeout_s = 120 s) so a slow-but-alive
    # prefill (e.g. cold-compile of a large bucket) isn't duplicated onto a
    # second worker while the first is still going to deliver.
    DEFAULT_VISIBILITY = 120.0

    def __init__(self, messaging, namespace: str = "public",
                 visibility: float = DEFAULT_VISIBILITY):
        self.messaging = messaging
        self.name = f"{namespace}.prefill_queue"
        self.visibility = visibility

    async def push(self, req: RemotePrefillRequest) -> None:
        await self.messaging.queue_push(self.name, req.to_wire())

    async def pop(self, timeout: Optional[float] = None):
        """Returns (RemotePrefillRequest, ack_fn) or None on timeout."""
        item = await self.messaging.queue_pop(
            self.name, timeout=timeout, visibility=self.visibility
        )
        if item is None:
            return None
        try:
            req = RemotePrefillRequest.from_wire(item.payload)
        except Exception:
            # poison message: it will never parse for any worker — ack it
            # away instead of crash-looping the whole prefill fleet
            import logging

            logging.getLogger(__name__).exception(
                "dropping malformed prefill queue item"
            )
            item.ack()
            return None
        return req, item.ack

    async def depth(self) -> int:
        return await self.messaging.queue_depth(self.name)
