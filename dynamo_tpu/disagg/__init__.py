"""Disaggregated prefill/decode serving ("xPyD").

The flagship capability of the reference (reference: docs/disagg_serving.md,
examples/llm/components/{worker,prefill_worker}.py): decode workers decide
per-request whether to prefill locally or enqueue the prompt on a shared
work queue; dedicated prefill workers pop the queue, compute the KV cache,
and push the blocks directly into the decode worker's device memory.

TPU mapping (SURVEY.md §7.6): NATS JetStream → the dynstore work queue
(ack + visibility-timeout redelivery); NIXL RDMA writes → the KV transfer
plane (`transfer.py`) moving paged blocks HBM→HBM with a host bounce,
descriptors registered in the discovery plane exactly like NIXL metadata.
"""

from .protocols import RemotePrefillRequest, PrefillQueue
from .router import DisaggRouter
from .transfer import KvTransferServer, KvTransferClient
from .coordinator import RemotePrefillCoordinator
from .prefill_worker import PrefillWorker

__all__ = [
    "RemotePrefillRequest",
    "PrefillQueue",
    "DisaggRouter",
    "KvTransferServer",
    "KvTransferClient",
    "RemotePrefillCoordinator",
    "PrefillWorker",
]
