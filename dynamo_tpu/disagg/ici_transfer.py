"""HBM-to-HBM KV block transfer over ICI/DCN via XLA collectives.

The TCP plane (disagg/transfer.py) moves KV blocks device → host → socket
→ host → device; correct everywhere, but bounded by PCIe + host copies.
When the prefill and decode workers share one ``jax.distributed`` process
group (same pod slice, or cross-slice over DCN), the bytes can instead
ride the interconnect directly: both sides enter one jitted ``ppermute``
over a two-device "peer" mesh — the sender's HBM shard lands in the
receiver's HBM with XLA routing it over ICI (or DCN between slices),
no host involvement. This is the TPU-native analog of the reference's
NIXL RDMA writes (docs/disagg_serving.md:60-100,
examples/llm/utils/nixl.py:59-109): the "registered memory descriptor"
becomes a mesh + sharding, and the "RDMA put" an XLA collective.

Control flow stays on the existing TCP channel (ordering + commit): the
sender first streams an ``ici_blocks`` header (ids, bucket — no payload),
then both sides enter the collective for the bucketed block arrays. A
lost peer surfaces as the collective's timeout rather than a hung socket.

The streamed prefill pipeline (disagg/prefill_worker.py) drives this
plane PIPELINED: while one ``send`` runs in an executor thread, the next
frame's device gather (and the next prefill chunk's compute) dispatch on
the event loop — safe because ``send`` only touches its own gathered
arrays, never the runner's donated cache buffers. The 1:1 pairing
discipline is preserved by construction: at most one collective is in
flight, and frame i+1's header is written only after frame i's ``send``
resolved, so an ``IciSendError`` always classifies against the last
header sent and the balancing rules below apply unchanged.

The payload STRIPES across device pairs: the mesh is [2, P] ("peer" ×
"pair") over min(sender-local, receiver-local) devices (rounded down to
a power of two), the bucketed block axis splits into P stripes, and the
single ppermute moves every stripe concurrently over its own link — so
transfer bandwidth scales with the local device count instead of being
bounded by one ICI link (each stripe is an independent peer hop in the
same collective program).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.compat import shard_map

logger = logging.getLogger(__name__)


class IciSendError(RuntimeError):
    """A send failed. ``entered`` tells the caller whether the collective
    was dispatched: False → the receiver's entry is still unpaired (send a
    balancing entry); True → the collective itself failed, which unwinds
    BOTH processes' entries (do not balance — there is nothing to pair)."""

    def __init__(self, cause: BaseException, entered: bool):
        super().__init__(f"ici send failed ({cause}); entered={entered}")
        self.cause = cause
        self.entered = entered


class IciKvTransfer:
    """One sender↔receiver pair of the collective transfer plane.

    Both processes construct this with the same ``(sender_rank,
    receiver_rank)`` and the same block shapes, then the sender calls
    :meth:`send` while the receiver calls :meth:`recv` — each call is one
    entry into the shared collective program, so the two sides MUST pair
    calls 1:1 (the ``ici_blocks`` header on the TCP channel provides that
    ordering). A sequence number rides INSIDE the collective payload: if
    a sender dies between header and collective, the orphaned receiver
    entry eventually pairs with a later send — the embedded seq then
    mismatches the header's and the payload is dropped instead of being
    scattered under the wrong request (see KvTransferServer).

    ``buckets`` defaults to the runner's block-op ladder so gathered
    shapes hit the compiled programs exactly; payloads larger than the
    top bucket must be chunked by the caller (PrefillWorker does).
    """

    def __init__(
        self,
        kv_block_shape: Tuple[Tuple[int, ...], Tuple[int, ...]],
        dtype,
        sender_rank: int = 0,
        receiver_rank: int = 1,
        buckets: Optional[Sequence[int]] = None,
    ):
        if buckets is None:
            from ..engine.model_runner import ModelRunner

            buckets = ModelRunner.BLOCK_OP_BUCKETS
        if jax.process_count() < 2:
            raise RuntimeError(
                "ICI kv transfer needs a multi-process jax.distributed "
                "world (use parallel.mesh.initialize_multihost)"
            )
        self.k_shape, self.v_shape = kv_block_shape  # [L, bs, KVH, D]-like
        self.dtype = dtype
        self.buckets = tuple(sorted(buckets))
        self.sender_rank = sender_rank
        self.receiver_rank = receiver_rank
        me = jax.process_index()
        if me not in (sender_rank, receiver_rank):
            raise RuntimeError(
                f"process {me} is neither sender {sender_rank} nor "
                f"receiver {receiver_rank}"
            )
        self.is_sender = me == sender_rank

        def local_devices_of(rank: int):
            devs = [d for d in jax.devices() if d.process_index == rank]
            if not devs:
                raise RuntimeError(f"no devices for process {rank}")
            return devs

        devs_s = local_devices_of(sender_rank)
        devs_r = local_devices_of(receiver_rank)
        # stripe across as many device PAIRS as both sides have; a power
        # of two keeps stripes even over the power-of-two buckets
        pairs = min(len(devs_s), len(devs_r))
        while pairs & (pairs - 1):
            pairs -= 1
        self.pairs = pairs
        # peer axis: [sender, receiver]; pair axis: the parallel links
        self.mesh = Mesh(
            np.array([devs_s[:pairs], devs_r[:pairs]]),
            ("peer", "pair"),
        )
        self.sharding = NamedSharding(self.mesh, P("peer", "pair"))
        self._programs: Dict[int, object] = {}

    # ---------- the collective ----------

    def _program(self, bucket: int):
        # key by EFFECTIVE bucket: every bucket below the pair count pads
        # to the same shapes, and duplicate XLA compiles of an identical
        # program are pure waste on compile-bound TPU hosts
        eff_key = self._eff_bucket(bucket)
        prog = self._programs.get(eff_key)
        if prog is not None:
            return prog

        def step(k_buf, v_buf, seq_buf):
            # peer 0 → peer 1 on every pair link at once; peer 1's (zero)
            # shard rotates back to 0 and is discarded — a pure shift
            # would need a conditional, and the dead shard costs the same
            # hop either way
            perm = [(0, 1), (1, 0)]
            return (
                jax.lax.ppermute(k_buf, "peer", perm),
                jax.lax.ppermute(v_buf, "peer", perm),
                jax.lax.ppermute(seq_buf, "peer", perm),
            )

        eff = self._eff_bucket(bucket)
        kb = self._local_shape(self.k_shape, eff)
        vb = self._local_shape(self.v_shape, eff)
        prog = jax.jit(
            shard_map(
                step, mesh=self.mesh,
                in_specs=(P("peer", "pair"), P("peer", "pair"),
                          P("peer", "pair")),
                out_specs=(P("peer", "pair"), P("peer", "pair"),
                           P("peer", "pair")),
            ),
        )
        self._programs[eff_key] = (prog, kb, vb)
        return self._programs[eff_key]

    def _eff_bucket(self, bucket: int) -> int:
        """Bucket padded so the block axis splits evenly across pairs
        (rounded UP to a multiple — a truncating split would silently
        drop the tail stripes of non-power-of-two custom buckets)."""
        return -(-bucket // self.pairs) * self.pairs

    def _local_shape(self, shape: Tuple[int, ...], eff: int) -> Tuple[int, ...]:
        # block arrays are [L, n, bs, heads, d]; the n axis carries the
        # (padded) bucket and stripes across pairs inside _global
        return (shape[0], eff) + tuple(shape[2:])

    def bucket_for(self, nblocks: int) -> int:
        for b in self.buckets:
            if nblocks <= b:
                return b
        return self.buckets[-1]

    def _global(self, local: jnp.ndarray) -> jax.Array:
        """Local payload [L, eff_bucket, ...] → [2, P, L, stripe, ...]
        peer×pair-sharded global (this side's row populated, the peer's
        addressed by its own process)."""
        st = local.shape[1] // self.pairs
        row = 0 if self.is_sender else 1
        shards = [
            jax.device_put(
                local[:, i * st : (i + 1) * st][None, None],
                self.mesh.devices[row, i],
            )
            for i in range(self.pairs)
        ]
        return jax.make_array_from_single_device_arrays(
            (2, self.pairs, local.shape[0], st) + tuple(local.shape[2:]),
            self.sharding,
            shards,
        )

    def _stage(self, bucket: int, k_local, v_local, seq: int):
        """Device-put the peer-sharded operands. Errors here are
        PRE-entry: the collective has not been dispatched yet."""
        prog, _, _ = self._program(bucket)
        return prog, (
            self._global(k_local),
            self._global(v_local),
            self._global(jnp.full((1, 8 * self.pairs), seq, jnp.int32)),
        )

    def _enter(self, bucket: int, k_local, v_local, seq: int):
        prog, args = self._stage(bucket, k_local, v_local, seq)
        ko, vo, so = prog(*args)
        # each process addresses its own row of pair stripes; reassemble
        # them in pair order. Pulling seq to host synchronizes, so
        # collective failures surface here.
        def assemble(out):
            stripes = sorted(out.addressable_shards, key=lambda s: s.index[1])
            parts = [s.data[0, 0] for s in stripes]
            if len(parts) == 1:
                return parts[0]
            # stripes are committed to their own devices; gather them onto
            # the first local device (device-to-device hop) to hand one
            # array downstream
            dev0 = parts[0].devices().pop()
            return jnp.concatenate(
                [jax.device_put(p, dev0) for p in parts], axis=1
            )

        k_shard = assemble(ko)
        v_shard = assemble(vo)
        seq_shard = int(np.asarray(so.addressable_shards[0].data).ravel()[0])
        return k_shard, v_shard, seq_shard

    # ---------- roles ----------

    def send(self, k_blocks, v_blocks, seq: int = 0) -> None:
        """Sender side: k/v [L, n<=top bucket, bs, heads, d] device or host.

        Raises IciSendError carrying whether the collective was entered —
        the caller needs that to keep the plane's 1:1 pairing discipline.
        """
        assert self.is_sender
        n = k_blocks.shape[1]
        if n > self.buckets[-1]:
            raise ValueError(
                f"{n} blocks exceed the top transfer bucket "
                f"{self.buckets[-1]}; chunk the payload"
            )
        bucket = self.bucket_for(n)
        eff = self._eff_bucket(bucket)
        entered = False
        t0 = time.monotonic()
        try:
            k = jnp.asarray(k_blocks, self.dtype)
            v = jnp.asarray(v_blocks, self.dtype)
            if n < eff:
                pad = [(0, 0)] * k.ndim
                pad[1] = (0, eff - n)
                k = jnp.pad(k, pad)
                v = jnp.pad(v, pad)
            prog, args = self._stage(bucket, k, v, seq)
            entered = True
            # synchronize: jax dispatch is async, and a collective failure
            # must surface HERE (inside the entered=True window) for the
            # caller's pairing-discipline classification — not at some
            # unrelated later device sync
            jax.block_until_ready(prog(*args))
        except BaseException as e:
            raise IciSendError(e, entered) from e
        # collective-plane observability: each frame's seq/size/duration
        # lands in the flight ring, so a stitched-trace gap over the ici
        # hop is attributable frame by frame (thread-safe append — this
        # runs on the prefill worker's executor thread)
        from ..telemetry.flight import flight_recorder

        flight_recorder().record(
            "disagg.ici_send", seq=int(seq), blocks=int(n),
            duration_s=round(time.monotonic() - t0, 4),
        )

    def send_balancing_entry(self, nblocks: int) -> None:
        """Pair an orphaned receiver entry (header out, collective never
        entered) with a poison payload: seq -1 matches no header, so the
        receiver drops it and the plane returns to 1:1. Synchronous: a
        failure must surface to the caller, which then abandons the
        plane rather than logging it healthy."""
        assert self.is_sender
        bucket = self.bucket_for(nblocks)
        _, kb, vb = self._program(bucket)
        prog, args = self._stage(
            bucket, jnp.zeros(kb, self.dtype),
            jnp.zeros(vb, self.dtype), -1,
        )
        jax.block_until_ready(prog(*args))

    def recv(self, nblocks: int):
        """Receiver side: returns (k, v, seq) — device arrays
        [L, n, bs, heads, d] plus the seq embedded by the sender."""
        assert not self.is_sender
        bucket = self.bucket_for(nblocks)
        (prog, kb, vb) = self._program(bucket)
        k0 = jnp.zeros(kb, self.dtype)
        v0 = jnp.zeros(vb, self.dtype)
        t0 = time.monotonic()
        k, v, seq = self._enter(bucket, k0, v0, 0)
        from ..telemetry.flight import flight_recorder

        flight_recorder().record(
            "disagg.ici_recv", seq=int(seq), blocks=int(nblocks),
            duration_s=round(time.monotonic() - t0, 4),
        )
        return k[:, :nblocks], v[:, :nblocks], seq


def kv_block_shapes(config) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """Transfer-plane block shapes for an EngineConfig — must agree on
    both workers (same reason block geometry is pinned in the MDC).

    Trailing dims are the LOGICAL kv dims: the runner's jitted gather
    strips the cache's lane padding and its scatter re-pads, so the
    interconnect moves only real bytes (matches the TCP wire format).
    """
    from ..models import resolve

    m = config.model
    arch = resolve(m)
    name = arch.__name__.rsplit(".", 1)[-1]
    l, bs = m.num_layers, config.kv_block_size
    if name == "deepseek":
        return (
            (l, 1, bs, 1, m.kv_lora_rank),
            (l, 1, bs, 1, m.qk_rope_head_dim),
        )
    d = m.head_dim
    return ((l, 1, bs, m.num_kv_heads, d), (l, 1, bs, m.num_kv_heads, d))
