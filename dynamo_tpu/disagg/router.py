"""Conditional disaggregation router: local vs. remote prefill decision.

Reference semantics (reference: lib/llm/src/disagg_router.rs:24-262 and the
Python port examples/llm/components/disagg_router.py): prefill goes remote
iff the un-cached prompt length exceeds a threshold AND the prefill queue
is not backed up. The threshold is *live-updatable* through a watched
discovery key, so operators can retune a running deployment — the analog
of the reference's etcd watch at
``public/components/disagg_router/models/chat/<model>``.
"""

from __future__ import annotations

import logging
from typing import Optional

import msgpack

logger = logging.getLogger(__name__)


class DisaggRouter:
    def __init__(
        self,
        max_local_prefill_length: int = 1000,
        max_prefill_queue_size: int = 2,
        model_name: Optional[str] = None,
        namespace: str = "public",
    ):
        self.max_local_prefill_length = max_local_prefill_length
        self.max_prefill_queue_size = max_prefill_queue_size
        self.model_name = model_name
        self.namespace = namespace
        self._watch_task = None
        self._watcher = None

    def config_key(self) -> str:
        return (
            f"{self.namespace}/components/disagg_router/models/"
            f"{self.model_name or '_default'}"
        )

    def prefill_remote(self, prefill_len: int, prefix_hit_len: int,
                       queue_depth: int) -> bool:
        """True → enqueue for remote prefill; False → prefill locally."""
        return (
            prefill_len - prefix_hit_len > self.max_local_prefill_length
            and queue_depth < self.max_prefill_queue_size
        )

    # ---------- dynamic config ----------

    def _apply(self, value: bytes) -> None:
        try:
            cfg = msgpack.unpackb(value, raw=False)
        except Exception:
            logger.warning("malformed disagg config update ignored")
            return
        if "max_local_prefill_length" in cfg:
            self.max_local_prefill_length = int(cfg["max_local_prefill_length"])
        if "max_prefill_queue_size" in cfg:
            self.max_prefill_queue_size = int(cfg["max_prefill_queue_size"])
        logger.info(
            "disagg router config: max_local_prefill_length=%d max_prefill_queue_size=%d",
            self.max_local_prefill_length, self.max_prefill_queue_size,
        )

    async def start(self, discovery, runtime=None) -> "DisaggRouter":
        """Load current config and watch for live updates."""
        snapshot, watcher = await discovery.watch_prefix(self.config_key())
        for value in snapshot.values():
            self._apply(value)
        self._watcher = watcher

        async def _watch():
            async for ev in watcher:
                if ev.type.value == "put":
                    self._apply(ev.value)

        import asyncio

        spawn = runtime.spawn if runtime is not None else asyncio.create_task
        self._watch_task = spawn(_watch())
        return self

    async def stop(self) -> None:
        if self._watcher is not None:
            self._watcher.cancel()

    @staticmethod
    async def publish_config(
        discovery, namespace: str, model_name: Optional[str],
        max_local_prefill_length: int, max_prefill_queue_size: int,
    ) -> None:
        """Operator-side: push a new threshold to all live routers."""
        key = (
            f"{namespace}/components/disagg_router/models/{model_name or '_default'}"
        )
        await discovery.kv_put(key, msgpack.packb({
            "max_local_prefill_length": max_local_prefill_length,
            "max_prefill_queue_size": max_prefill_queue_size,
        }, use_bin_type=True))
