"""KV transfer plane: push paged KV blocks into a remote engine's cache.

The TPU-native stand-in for NIXL RDMA writes (reference:
docs/disagg_serving.md:60-100, examples/llm/utils/nixl.py:59-109 — prefill
worker loads the decode worker's memory descriptors from etcd and writes
computed KV straight into its GPU blocks). Here each decode engine runs a
``KvTransferServer``; its (host, port, engine_id) descriptor is registered
in the discovery plane under the component, and prefill workers dial it and
stream block frames. Device↔host movement uses the runner's jitted
gather/scatter programs (XLA's fused gather/scatter is the analog of the
reference's CUDA copy kernel, block_copy.cu:40-758); frames are chunked so
the receive side overlaps scatter with the next frame's network read —
mirroring CopyStream::trigger_layer per-layer overlap semantics.

Wire format, length-prefixed msgpack header + raw payloads:

  {type: "blocks", request_id, trace_id?, block_ids, shape, dtype, k_bytes, v_bytes}
  <k raw bytes> <v raw bytes>
  {type: "commit", request_id, first_token, logprob, generated, spans?}

Read-only block serve (the cluster KV fabric, kv/fabric.py) rides the
same framing in the other direction — a peer asks for a sequence-hash
chain and this engine streams whatever prefix run it still holds::

  → {type: "pull", hashes, chunk_blocks, trace_id?}
  ← {type: "pull_blocks", shape, dtype, k_bytes, v_bytes} <k> <v>  (per chunk)
  ← {type: "pull_end", served}

``spans`` is the prefill worker's span export for the cluster-stitched
trace (telemetry/stitch.py): its wall-clock span marks plus the
request-receipt/commit-send timestamps the decode side folds into a
per-hop clock-offset estimate. ``trace_id`` rides payload frames so
poison/drop flight events stay attributable to the ingress trace.

The commit is acked with one framed byte: \x01 = committed, \x00 = nacked
(an earlier payload frame for the request was dropped — the decode side
must NOT resume over blocks that were never scattered; its request falls
back to local prefill via the coordinator's prefill_timeout_s).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import inspect
import logging
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import msgpack
import numpy as np

logger = logging.getLogger(__name__)

MAX_HEADER = 1 << 20
# dropped-payload bookkeeping: ids are removed when their commit is
# nacked; requests that never commit would otherwise accumulate forever.
# TTL >> any sane commit delay (the decode side's prefill timeout is
# 120 s), so expiry never un-poisons a commit that could still arrive;
# the count cap is a last-resort bound and LOGS what it evicts.
MAX_DROPPED = 4096
DROPPED_TTL_S = 600.0


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def transfer_key(namespace: str, component: str, engine_id: str) -> str:
    return f"{namespace}/components/{component}/kv_transfer/{engine_id}"


async def _read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    return await reader.readexactly(n)


class KvTransferServer:
    """Receives block frames and scatters them into the local paged cache."""

    def __init__(
        self,
        scatter: Callable[[str, Sequence[int], np.ndarray, np.ndarray], None],
        # on_commit(request_id, first_token, logprob, top, spans) — spans
        # is the sender's span export for the stitched trace (or None)
        on_commit: Callable[..., None],
        authorize: Optional[Callable[[str, Sequence[int]], bool]] = None,
        host: str = "127.0.0.1",
        ici_recv: Optional[Callable[[int], tuple]] = None,
        ici_rank: Optional[int] = None,
        ici_recv_timeout_s: float = 120.0,
        pull_source=None,  # Optional[Callable[[List[int]], PullGrant]]
    ):
        # scatter(request_id, block_ids, k, v) — may return an awaitable; an
        # async scatter MUST re-validate the request id after any await (the
        # request can be cancelled mid-flight and its blocks reallocated)
        self.scatter = scatter
        self.on_commit = on_commit
        # guards against late frames for cancelled/unknown requests writing
        # into reallocated blocks
        self.authorize = authorize or (lambda request_id, ids: True)
        self.host = host
        # ici_recv(nblocks) -> (k, v, seq): enter the collective transfer
        # plane (disagg/ici_transfer.py) and return device arrays plus the
        # seq the sender embedded in the payload (checked against the
        # header's — load-bearing for mis-pair detection). The TCP frame
        # "ici_blocks" is then control-only — ids ride the socket, bytes
        # ride the interconnect. ici_rank is this receiver's jax process
        # index, advertised so senders only pick ici when THEIR plane
        # pairs with this engine.
        self.ici_recv = ici_recv
        self.ici_rank = ici_rank
        # read-only block serve (the cluster KV fabric, kv/fabric.py):
        # pull_source(hashes) resolves + PINS the longest locally-held
        # run of a sequence-hash chain and hands back a grant whose
        # gather_frame packs wire frames off-loop; release() unpins and
        # MUST run exactly once — the handler's finally owns it, so a
        # connection dying mid-serve can never leave blocks fenced
        self.pull_source = pull_source
        # generous default: the first recv compiles the collective program
        self.ici_recv_timeout_s = ici_recv_timeout_s
        # collective entries are strictly ordered — serialize receives
        # across connections (the payloads pair with headers 1:1)
        self._ici_lock = asyncio.Lock()
        # request ids with a dropped payload frame (seq mismatch, revoked
        # authorization, recv timeout): their commit must be NACKED — the
        # decode side would otherwise resume over blocks that were never
        # scattered, silently corrupting the stream. id -> monotonic time
        # of the drop (insertion-ordered; TTL + logged-cap pruning).
        self._dropped: Dict[str, float] = {}
        self.port: Optional[int] = None
        self._server: Optional[asyncio.AbstractServer] = None

    def _mark_dropped(self, request_id: str,
                      trace_id: Optional[str] = None) -> None:
        from ..telemetry.flight import flight_recorder

        now = time.monotonic()
        flight_recorder().record(
            "disagg.poison", request_id=request_id, trace_id=trace_id,
        )
        self._dropped.pop(request_id, None)
        self._dropped[request_id] = now
        # TTL expiry (insertion order == time order): anything this old
        # can no longer see a commit — the decode side gave up on the
        # request minutes ago
        for rid, t in list(self._dropped.items()):
            if now - t <= DROPPED_TTL_S:
                break
            del self._dropped[rid]
        while len(self._dropped) > MAX_DROPPED:
            rid, _ = next(iter(self._dropped.items()))
            del self._dropped[rid]
            # un-poisoning is the corruption this set exists to prevent —
            # if this ever fires under real load, raise the cap
            logger.error(
                "dropped-payload set over cap (%d); evicting %s — a late "
                "commit for it would now be accepted", MAX_DROPPED, rid,
            )

    @staticmethod
    def _call_in_daemon_thread(fn, *args) -> "concurrent.futures.Future":
        """Run fn on a fresh DAEMON thread. A stranded collective recv
        blocks its thread forever; ThreadPoolExecutor workers are
        non-daemon and joined by an atexit hook, so a wedged one would
        hang interpreter shutdown — daemon threads don't."""
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def work():
            try:
                result = fn(*args)
            except BaseException as e:
                if not fut.cancelled():
                    fut.set_exception(e)
            else:
                if not fut.cancelled():
                    fut.set_result(result)

        threading.Thread(target=work, daemon=True, name="ici-recv").start()
        return fut

    async def start(self) -> "KvTransferServer":
        self._server = await asyncio.start_server(self._handle, self.host, 0)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def descriptor(self) -> dict:
        # modes let the prefill side pick a payload path BOTH ends support
        # — sending an ici frame to a tcp-only server would strand the
        # sender inside a collective that never pairs
        modes = ["tcp"] + (["ici"] if self.ici_recv is not None else [])
        if self.pull_source is not None:
            modes.append("pull")
        desc = {"host": self.host, "port": self.port, "modes": modes}
        if self.ici_rank is not None:
            desc["ici_rank"] = self.ici_rank
        return desc

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        # request ids with payload frames on THIS connection whose commit
        # has not arrived yet: a connection that dies mid-stream leaves
        # those requests' caches partially scattered, so their commits
        # must be nacked — streamed transfer means a frame can be on the
        # wire while later chunks are still computing, and a sender crash
        # between frames must never let a (redelivered) commit resume
        # decode over a cache whose provenance this receiver can't prove
        streaming: set = set()
        try:
            while True:
                try:
                    raw_len = await _read_exact(reader, 4)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                (hlen,) = struct.unpack(">I", raw_len)
                if hlen > MAX_HEADER:
                    logger.error("transfer header too large: %d", hlen)
                    return
                header = msgpack.unpackb(await _read_exact(reader, hlen), raw=False)
                mtype = header.get("type")
                if mtype in ("blocks", "ici_blocks"):
                    # mark BEFORE the payload read: dying mid-payload is
                    # the same partial-stream hazard as dying between
                    # frames
                    streaming.add(header["request_id"])
                if mtype == "blocks":
                    k_raw = await _read_exact(reader, header["k_bytes"])
                    v_raw = await _read_exact(reader, header["v_bytes"])
                    if not self.authorize(header["request_id"], header["block_ids"]):
                        # request gone — drop the frame; a later commit for
                        # this id must be nacked, not resumed-on
                        self._mark_dropped(header["request_id"],
                                           header.get("trace_id"))
                        continue
                    dtype = _np_dtype(header["dtype"])
                    shape = tuple(header["shape"])
                    k = np.frombuffer(k_raw, dtype=dtype).reshape(shape)
                    v = np.frombuffer(v_raw, dtype=dtype).reshape(shape)
                    # scatter may be a coroutine that stages the host→device
                    # copy off-loop so decode streaming isn't stalled
                    result = self.scatter(header["request_id"], header["block_ids"], k, v)
                    if inspect.isawaitable(result):
                        await result
                elif mtype == "ici_blocks":
                    ids = header["block_ids"]
                    if self.ici_recv is None:
                        logger.error("ici_blocks frame but no ici plane")
                        return
                    # the sender has entered (or is about to enter) the
                    # collective — the receive MUST happen even for a
                    # cancelled request, or both sides deadlock; authorize
                    # decides only whether the payload is scattered. The
                    # receive is BOUNDED: a sender that died after the
                    # header leaves an entry that never pairs, and an
                    # unbounded wait would strand this handler (and its
                    # thread) forever.
                    try:
                        async with self._ici_lock:
                            k, v, seq = await asyncio.wait_for(
                                asyncio.wrap_future(
                                    self._call_in_daemon_thread(
                                        self.ici_recv, len(ids)
                                    )
                                ),
                                timeout=self.ici_recv_timeout_s,
                            )
                    except asyncio.TimeoutError:
                        # receiver-side plane abandonment: the stranded
                        # recv owns the plane's only executor thread, so
                        # the plane is unusable — stop advertising it.
                        # Future ici frames (this or any connection) error
                        # and close, which the sender surfaces as its own
                        # abandonment; this request's commit gets nacked
                        # and the decode side falls back to local prefill.
                        logger.error(
                            "ici recv timed out after %.0fs (sender lost "
                            "after header?) — abandoning the ici plane on "
                            "the receiver side",
                            self.ici_recv_timeout_s,
                        )
                        self.ici_recv = None
                        self._mark_dropped(header["request_id"],
                                           header.get("trace_id"))
                        continue
                    if seq != header.get("seq", 0):
                        # a sender died between header and collective and
                        # this entry paired with a LATER send — the payload
                        # belongs to some other request; dropping it loses
                        # that transfer (its redelivery re-sends) but never
                        # scatters bytes under the wrong ids
                        logger.error(
                            "ici transfer seq mismatch (header %s, payload "
                            "%s) — dropping mis-paired payload",
                            header.get("seq"), seq,
                        )
                        self._mark_dropped(header["request_id"],
                                           header.get("trace_id"))
                        continue
                    if not self.authorize(header["request_id"], ids):
                        self._mark_dropped(header["request_id"],
                                           header.get("trace_id"))
                        continue  # request gone — drop the received blocks
                    result = self.scatter(header["request_id"], ids, k, v)
                    if inspect.isawaitable(result):
                        await result
                elif mtype == "pull":
                    # read-only block serve (cluster KV fabric): stream
                    # the longest locally-resident run of the requested
                    # hash chain back over THIS connection
                    await self._serve_pull(header, writer)
                elif mtype == "commit":
                    rid = header["request_id"]
                    streaming.discard(rid)
                    if rid in self._dropped:
                        # a payload frame for this request was dropped —
                        # its KV blocks were never (fully) scattered, so
                        # committing would resume decode over garbage.
                        # Nack: the sender releases its side, the decode
                        # side's pending future times out and the request
                        # re-prefills locally.
                        del self._dropped[rid]
                        logger.warning(
                            "nacking commit for %s: an earlier payload "
                            "frame was dropped", rid,
                        )
                        writer.write(struct.pack(">I", 1) + b"\x00")
                        await writer.drain()
                        continue
                    top = header.get("top")
                    self.on_commit(
                        rid, header["first_token"],
                        header.get("logprob"),
                        {int(k): float(v) for k, v in top.items()}
                        if top else None,
                        header.get("spans"),
                    )
                    # ack the commit so the sender can safely release blocks
                    writer.write(struct.pack(">I", 1) + b"\x01")
                    await writer.drain()
                else:
                    logger.error("unknown transfer frame type %r", mtype)
                    return
        except Exception:
            logger.exception("kv transfer connection failed")
        finally:
            for rid in streaming:
                logger.warning(
                    "transfer connection closed mid-stream for %s; "
                    "poisoning its commit (decode will fall back to "
                    "local prefill)", rid,
                )
                self._mark_dropped(rid)
            writer.close()

    async def _serve_pull(self, header: dict,
                          writer: asyncio.StreamWriter) -> None:
        """Serve one ``pull`` frame: resolve the longest locally-held
        run of the requested sequence-hash chain and stream it back as
        ``pull_blocks`` frames + a ``pull_end`` trailer.

        Strictly read-only: blocks are pinned for the duration (the
        grant), gathered and byte-packed off-loop, and unpinned in the
        ``finally`` — a puller that vanishes mid-stream costs this
        engine nothing but the frames already sent.
        """
        from ..telemetry.flight import flight_recorder
        from ..utils import faults

        hashes = [int(h) for h in header.get("hashes") or []]
        chunk = max(1, int(header.get("chunk_blocks", 16)))
        grant = self.pull_source(hashes) if self.pull_source else None
        flight_recorder().record(
            "kv_fabric.serve", trace_id=header.get("trace_id"),
            asked=len(hashes), served=len(grant) if grant else 0,
        )
        if grant is None:
            hdr = msgpack.packb({"type": "pull_end", "served": 0},
                                use_bin_type=True)
            writer.write(struct.pack(">I", len(hdr)) + hdr)
            await writer.drain()
            return
        try:
            n = len(grant)
            for lo in range(0, n, chunk):
                if faults.fire("transfer_conn_drop"):
                    # chaos site: the serving side dies mid-stream — the
                    # puller must fall back to local recompute with its
                    # reservation freed and nothing registered
                    writer.close()
                    return
                kb, vb, shape, dtype = await grant.gather_frame(
                    lo, min(lo + chunk, n)
                )
                hdr = msgpack.packb({
                    "type": "pull_blocks", "shape": shape, "dtype": dtype,
                    "k_bytes": len(kb), "v_bytes": len(vb),
                }, use_bin_type=True)
                writer.write(struct.pack(">I", len(hdr)) + hdr)
                writer.write(kb)
                writer.write(vb)
                await writer.drain()
            hdr = msgpack.packb({"type": "pull_end", "served": n},
                                use_bin_type=True)
            writer.write(struct.pack(">I", len(hdr)) + hdr)
            await writer.drain()
        finally:
            grant.release()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class KvTransferClient:
    """Prefill-side connection pushing block frames to one decode engine."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None

    async def connect(self) -> "KvTransferClient":
        self.reader, self.writer = await asyncio.open_connection(self.host, self.port)
        return self

    def _send_header(self, header: dict) -> None:
        data = msgpack.packb(header, use_bin_type=True)
        self.writer.write(struct.pack(">I", len(data)) + data)

    async def send_blocks(
        self,
        request_id: str,
        block_ids: List[int],
        k_blocks: np.ndarray,   # [L, n, bs, KVH, D]
        v_blocks: np.ndarray,
        chunk_blocks: int = 16,
        trace_id: Optional[str] = None,
    ) -> None:
        """Stream blocks in chunks so the receiver overlaps scatter w/ reads."""
        from ..utils import faults

        n = len(block_ids)
        assert k_blocks.shape[1] == n
        for i in range(0, n, chunk_blocks):
            if faults.fire("transfer_conn_drop"):
                # chaos site: the sender dies mid-stream — the receiver
                # must poison this request's commit (utils/faults.py)
                self.writer.close()
                raise ConnectionResetError(
                    "fault injected: transfer_conn_drop"
                )
            ids = block_ids[i : i + chunk_blocks]
            k = np.ascontiguousarray(k_blocks[:, i : i + len(ids)])
            v = np.ascontiguousarray(v_blocks[:, i : i + len(ids)])
            kb, vb = k.tobytes(), v.tobytes()
            header = {
                "type": "blocks",
                "request_id": request_id,
                "block_ids": list(map(int, ids)),
                "shape": list(k.shape),
                "dtype": k.dtype.name,
                "k_bytes": len(kb),
                "v_bytes": len(vb),
            }
            if trace_id:
                header["trace_id"] = trace_id
            self._send_header(header)
            self.writer.write(kb)
            self.writer.write(vb)
            await self.writer.drain()

    async def send_ici_blocks(
        self, request_id: str, block_ids: List[int], seq: int = 0,
        trace_id: Optional[str] = None,
    ) -> None:
        """Announce a collective-plane transfer: ids over TCP, bytes over
        ICI/DCN (the caller enters IciKvTransfer.send(..., seq=seq) after
        this drains; the receiver cross-checks seq against the payload)."""
        header = {
            "type": "ici_blocks",
            "request_id": request_id,
            "block_ids": list(map(int, block_ids)),
            "seq": int(seq),
        }
        if trace_id:
            header["trace_id"] = trace_id
        self._send_header(header)
        await self.writer.drain()

    async def send_commit(self, request_id: str, first_token: int,
                          logprob: Optional[float] = None,
                          top: Optional[dict] = None,
                          spans: Optional[dict] = None) -> bool:
        """Returns True if the receiver committed, False if it nacked
        (a payload frame was dropped — the decode side will re-prefill
        locally; the sender just releases its resources either way).
        ``spans`` piggybacks the sender's span export for the stitched
        trace — its wall-clock marks + recv/send timestamps."""
        self._send_header({
            "type": "commit",
            "request_id": request_id,
            "first_token": int(first_token),
            "logprob": None if logprob is None else float(logprob),
            # first-token top-logprob alternatives (string token-id keys
            # for the msgpack strict decode)
            "top": {str(k): float(v) for k, v in top.items()} if top else None,
            "spans": spans,
        })
        await self.writer.drain()
        # wait for the receiver's ack — after this the decode side owns the KV
        ack = await _read_exact(self.reader, 5)
        return ack[-1:] == b"\x01"

    async def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            # dynlint: allow(silent-except) - best-effort close of a possibly-dead peer
            except Exception:
                pass
