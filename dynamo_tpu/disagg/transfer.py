"""KV transfer plane: push paged KV blocks into a remote engine's cache.

The TPU-native stand-in for NIXL RDMA writes (reference:
docs/disagg_serving.md:60-100, examples/llm/utils/nixl.py:59-109 — prefill
worker loads the decode worker's memory descriptors from etcd and writes
computed KV straight into its GPU blocks). Here each decode engine runs a
``KvTransferServer``; its (host, port, engine_id) descriptor is registered
in the discovery plane under the component, and prefill workers dial it and
stream block frames. Device↔host movement uses the runner's jitted
gather/scatter programs (XLA's fused gather/scatter is the analog of the
reference's CUDA copy kernel, block_copy.cu:40-758); frames are chunked so
the receive side overlaps scatter with the next frame's network read —
mirroring CopyStream::trigger_layer per-layer overlap semantics.

Wire format, length-prefixed msgpack header + raw payloads:

  {type: "blocks", request_id, block_ids, shape, dtype, k_bytes, v_bytes}
  <k raw bytes> <v raw bytes>
  {type: "commit", request_id, first_token, logprob, generated}
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import struct
from typing import Callable, Dict, List, Optional, Sequence

import msgpack
import numpy as np

logger = logging.getLogger(__name__)

MAX_HEADER = 1 << 20


def _np_dtype(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def transfer_key(namespace: str, component: str, engine_id: str) -> str:
    return f"{namespace}/components/{component}/kv_transfer/{engine_id}"


async def _read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    return await reader.readexactly(n)


class KvTransferServer:
    """Receives block frames and scatters them into the local paged cache."""

    def __init__(
        self,
        scatter: Callable[[str, Sequence[int], np.ndarray, np.ndarray], None],
        on_commit: Callable[[str, int, Optional[float]], None],
        authorize: Optional[Callable[[str, Sequence[int]], bool]] = None,
        host: str = "127.0.0.1",
    ):
        # scatter(request_id, block_ids, k, v) — may return an awaitable; an
        # async scatter MUST re-validate the request id after any await (the
        # request can be cancelled mid-flight and its blocks reallocated)
        self.scatter = scatter
        self.on_commit = on_commit
        # guards against late frames for cancelled/unknown requests writing
        # into reallocated blocks
        self.authorize = authorize or (lambda request_id, ids: True)
        self.host = host
        self.port: Optional[int] = None
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> "KvTransferServer":
        self._server = await asyncio.start_server(self._handle, self.host, 0)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def descriptor(self) -> dict:
        return {"host": self.host, "port": self.port}

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    raw_len = await _read_exact(reader, 4)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                (hlen,) = struct.unpack(">I", raw_len)
                if hlen > MAX_HEADER:
                    logger.error("transfer header too large: %d", hlen)
                    return
                header = msgpack.unpackb(await _read_exact(reader, hlen), raw=False)
                mtype = header.get("type")
                if mtype == "blocks":
                    k_raw = await _read_exact(reader, header["k_bytes"])
                    v_raw = await _read_exact(reader, header["v_bytes"])
                    if not self.authorize(header["request_id"], header["block_ids"]):
                        continue  # request gone — drop the frame
                    dtype = _np_dtype(header["dtype"])
                    shape = tuple(header["shape"])
                    k = np.frombuffer(k_raw, dtype=dtype).reshape(shape)
                    v = np.frombuffer(v_raw, dtype=dtype).reshape(shape)
                    # scatter may be a coroutine that stages the host→device
                    # copy off-loop so decode streaming isn't stalled
                    result = self.scatter(header["request_id"], header["block_ids"], k, v)
                    if inspect.isawaitable(result):
                        await result
                elif mtype == "commit":
                    self.on_commit(
                        header["request_id"], header["first_token"],
                        header.get("logprob"),
                    )
                    # ack the commit so the sender can safely release blocks
                    writer.write(struct.pack(">I", 1) + b"\x01")
                    await writer.drain()
                else:
                    logger.error("unknown transfer frame type %r", mtype)
                    return
        except Exception:
            logger.exception("kv transfer connection failed")
        finally:
            writer.close()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class KvTransferClient:
    """Prefill-side connection pushing block frames to one decode engine."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None

    async def connect(self) -> "KvTransferClient":
        self.reader, self.writer = await asyncio.open_connection(self.host, self.port)
        return self

    def _send_header(self, header: dict) -> None:
        data = msgpack.packb(header, use_bin_type=True)
        self.writer.write(struct.pack(">I", len(data)) + data)

    async def send_blocks(
        self,
        request_id: str,
        block_ids: List[int],
        k_blocks: np.ndarray,   # [L, n, bs, KVH, D]
        v_blocks: np.ndarray,
        chunk_blocks: int = 16,
    ) -> None:
        """Stream blocks in chunks so the receiver overlaps scatter w/ reads."""
        n = len(block_ids)
        assert k_blocks.shape[1] == n
        for i in range(0, n, chunk_blocks):
            ids = block_ids[i : i + chunk_blocks]
            k = np.ascontiguousarray(k_blocks[:, i : i + len(ids)])
            v = np.ascontiguousarray(v_blocks[:, i : i + len(ids)])
            kb, vb = k.tobytes(), v.tobytes()
            self._send_header({
                "type": "blocks",
                "request_id": request_id,
                "block_ids": list(map(int, ids)),
                "shape": list(k.shape),
                "dtype": k.dtype.name,
                "k_bytes": len(kb),
                "v_bytes": len(vb),
            })
            self.writer.write(kb)
            self.writer.write(vb)
            await self.writer.drain()

    async def send_commit(self, request_id: str, first_token: int,
                          logprob: Optional[float] = None) -> None:
        self._send_header({
            "type": "commit",
            "request_id": request_id,
            "first_token": int(first_token),
            "logprob": None if logprob is None else float(logprob),
        })
        await self.writer.drain()
        # wait for the receiver's ack — after this the decode side owns the KV
        await _read_exact(self.reader, 5)

    async def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except Exception:
                pass
