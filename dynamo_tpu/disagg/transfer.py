"""KV transfer endpoints: push paged KV blocks into a remote engine's cache.

The TPU-native stand-in for NIXL RDMA writes (reference:
docs/disagg_serving.md:60-100, examples/llm/utils/nixl.py:59-109 — prefill
worker loads the decode worker's memory descriptors from etcd and writes
computed KV straight into its GPU blocks). Here each decode engine runs a
``KvTransferServer``; its (host, port, engine_id) descriptor is registered
in the discovery plane under the component, and prefill workers dial it and
stream block frames. Device↔host movement uses the runner's jitted
gather/scatter programs (XLA's fused gather/scatter is the analog of the
reference's CUDA copy kernel, block_copy.cu:40-758); frames are chunked so
the receive side overlaps scatter with the next frame's network read —
mirroring CopyStream::trigger_layer per-layer overlap semantics.

Framing, payload backends (tcp inline vs ici collective), pipelining,
and the poison discipline live in the unified transfer plane
(``dynamo_tpu/transfer/``, docs/transfer_plane.md); this module is the
disagg plane's protocol on top of it:

  {type: "blocks", request_id, trace_id?, block_ids, shape, dtype, k_bytes, v_bytes}
  <k raw bytes> <v raw bytes>
  {type: "ici_blocks", request_id, block_ids, seq}        (payload rides ICI)
  {type: "commit", request_id, first_token, logprob, generated, spans?}

Read-only block serve (the cluster KV fabric, kv/fabric.py) rides the
same framing in the other direction — a peer asks for a sequence-hash
chain and this engine streams whatever prefix run it still holds::

  → {type: "pull", hashes, chunk_blocks, backend?, trace_id?}
  ← {type: "pull_blocks", shape, dtype, k_bytes, v_bytes} <k> <v>  (tcp chunk)
  ← {type: "pull_ici_blocks", nblocks, seq}           (ici chunk, header-only)
  ← {type: "pull_end", served}

``spans`` is the prefill worker's span export for the cluster-stitched
trace (telemetry/stitch.py): its wall-clock span marks plus the
request-receipt/commit-send timestamps the decode side folds into a
per-hop clock-offset estimate. ``trace_id`` rides payload frames so
poison/drop flight events stay attributable to the ingress trace.

The commit is acked with one framed byte: \x01 = committed, \x00 = nacked
(an earlier payload frame for the request was dropped — the decode side
must NOT resume over blocks that were never scattered; its request falls
back to local prefill via the coordinator's prefill_timeout_s).
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import struct
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..transfer.framing import (
    MAX_HEADER,
    np_dtype,
    pack_frame,
    read_exact,
    read_header,
)
from ..transfer.ici import IciBackend, bounded_collective_recv
from ..transfer.plane import PoisonSet, maybe_drop_connection
from ..transfer.tcp import TcpBackend

logger = logging.getLogger(__name__)


def transfer_key(namespace: str, component: str, engine_id: str) -> str:
    return f"{namespace}/components/{component}/kv_transfer/{engine_id}"


class KvTransferServer:
    """Receives block frames and scatters them into the local paged cache."""

    def __init__(
        self,
        scatter: Callable[[str, Sequence[int], np.ndarray, np.ndarray], None],
        # on_commit(request_id, first_token, logprob, top, spans) — spans
        # is the sender's span export for the stitched trace (or None)
        on_commit: Callable[..., None],
        authorize: Optional[Callable[[str, Sequence[int]], bool]] = None,
        host: str = "127.0.0.1",
        ici_recv: Optional[Callable[[int], tuple]] = None,
        ici_rank: Optional[int] = None,
        ici_recv_timeout_s: float = 120.0,
        pull_source=None,  # Optional[Callable[[List[int]], PullGrant]]
        ici_send=None,     # collective SENDER endpoint for ici pull serving
    ):
        # scatter(request_id, block_ids, k, v) — may return an awaitable; an
        # async scatter MUST re-validate the request id after any await (the
        # request can be cancelled mid-flight and its blocks reallocated)
        self.scatter = scatter
        self.on_commit = on_commit
        # guards against late frames for cancelled/unknown requests writing
        # into reallocated blocks
        self.authorize = authorize or (lambda request_id, ids: True)
        self.host = host
        # ici_recv(nblocks) -> (k, v, seq): enter the collective transfer
        # plane (disagg/ici_transfer.py) and return device arrays plus the
        # seq the sender embedded in the payload (checked against the
        # header's — load-bearing for mis-pair detection). The TCP frame
        # "ici_blocks" is then control-only — ids ride the socket, bytes
        # ride the interconnect. ici_rank is this receiver's jax process
        # index, advertised so senders only pick ici when THEIR plane
        # pairs with this engine.
        self.ici_recv = ici_recv
        self.ici_rank = ici_rank
        # read-only block serve (the cluster KV fabric, kv/fabric.py):
        # pull_source(hashes) resolves + PINS the longest locally-held
        # run of a sequence-hash chain and hands back a grant whose
        # gather_frame packs wire frames off-loop; release() unpins and
        # MUST run exactly once — the handler's finally owns it, so a
        # connection dying mid-serve can never leave blocks fenced
        self.pull_source = pull_source
        # the fabric's ici serve half: a collective sender endpoint so a
        # negotiated pull moves blocks device-to-device (host touches
        # only headers); wrapped in the backend that owns the pairing/
        # abandonment discipline
        if ici_send is not None and not isinstance(ici_send, IciBackend):
            ici_send = IciBackend(ici_send)
        self.ici_send: Optional[IciBackend] = ici_send
        # generous default: the first recv compiles the collective program
        self.ici_recv_timeout_s = ici_recv_timeout_s
        # collective entries are strictly ordered — serialize receives
        # across connections (the payloads pair with headers 1:1)
        self._ici_lock = asyncio.Lock()
        # request ids with a dropped payload frame (seq mismatch, revoked
        # authorization, recv timeout): their commit must be NACKED — the
        # decode side would otherwise resume over blocks that were never
        # scattered, silently corrupting the stream.
        self._poison = PoisonSet("disagg")
        self.port: Optional[int] = None
        self._server: Optional[asyncio.AbstractServer] = None

    def _mark_dropped(self, request_id: str,
                      trace_id: Optional[str] = None,
                      backend: str = "tcp", reason: str = "") -> None:
        self._poison.mark(request_id, trace_id=trace_id, backend=backend,
                          reason=reason)

    async def start(self) -> "KvTransferServer":
        self._server = await asyncio.start_server(self._handle, self.host, 0)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def descriptor(self) -> dict:
        # modes let the prefill side pick a payload path BOTH ends support
        # — sending an ici frame to a tcp-only server would strand the
        # sender inside a collective that never pairs
        ici_ok = (self.ici_recv is not None
                  or (self.ici_send is not None and self.ici_send.alive))
        modes = ["tcp"] + (["ici"] if ici_ok else [])
        if self.pull_source is not None:
            modes.append("pull")
        desc = {"host": self.host, "port": self.port, "modes": modes}
        if self.ici_rank is not None:
            desc["ici_rank"] = self.ici_rank
        return desc

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        # request ids with payload frames on THIS connection whose commit
        # has not arrived yet: a connection that dies mid-stream leaves
        # those requests' caches partially scattered, so their commits
        # must be nacked — streamed transfer means a frame can be on the
        # wire while later chunks are still computing, and a sender crash
        # between frames must never let a (redelivered) commit resume
        # decode over a cache whose provenance this receiver can't prove
        streaming: set = set()
        try:
            while True:
                try:
                    header = await read_header(reader, "transfer")
                except ValueError as e:
                    logger.error("%s", e)
                    return
                if header is None:
                    return
                mtype = header.get("type")
                if mtype in ("blocks", "ici_blocks"):
                    # mark BEFORE the payload read: dying mid-payload is
                    # the same partial-stream hazard as dying between
                    # frames
                    streaming.add(header["request_id"])
                if mtype == "blocks":
                    k, v = await TcpBackend.recv_blocks(reader, header)
                    if not self.authorize(header["request_id"], header["block_ids"]):
                        # request gone — drop the frame; a later commit for
                        # this id must be nacked, not resumed-on
                        self._mark_dropped(header["request_id"],
                                           header.get("trace_id"),
                                           reason="unauthorized")
                        continue
                    # scatter may be a coroutine that stages the host→device
                    # copy off-loop so decode streaming isn't stalled
                    result = self.scatter(header["request_id"], header["block_ids"], k, v)
                    if inspect.isawaitable(result):
                        await result
                elif mtype == "ici_blocks":
                    ids = header["block_ids"]
                    if self.ici_recv is None:
                        logger.error("ici_blocks frame but no ici plane")
                        return
                    # the sender has entered (or is about to enter) the
                    # collective — the receive MUST happen even for a
                    # cancelled request, or both sides deadlock; authorize
                    # decides only whether the payload is scattered. The
                    # receive is BOUNDED: a sender that died after the
                    # header leaves an entry that never pairs, and an
                    # unbounded wait would strand this handler (and its
                    # thread) forever.
                    try:
                        async with self._ici_lock:
                            k, v, seq = await bounded_collective_recv(
                                self.ici_recv, len(ids),
                                self.ici_recv_timeout_s,
                            )
                    except asyncio.TimeoutError:
                        # receiver-side plane abandonment: the stranded
                        # recv owns the plane's only executor thread, so
                        # the plane is unusable — stop advertising it.
                        # Future ici frames (this or any connection) error
                        # and close, which the sender surfaces as its own
                        # abandonment; this request's commit gets nacked
                        # and the decode side falls back to local prefill.
                        logger.error(
                            "ici recv timed out after %.0fs (sender lost "
                            "after header?) — abandoning the ici plane on "
                            "the receiver side",
                            self.ici_recv_timeout_s,
                        )
                        self.ici_recv = None
                        self._mark_dropped(header["request_id"],
                                           header.get("trace_id"),
                                           backend="ici",
                                           reason="recv_timeout")
                        continue
                    if seq != header.get("seq", 0):
                        # a sender died between header and collective and
                        # this entry paired with a LATER send — the payload
                        # belongs to some other request; dropping it loses
                        # that transfer (its redelivery re-sends) but never
                        # scatters bytes under the wrong ids
                        logger.error(
                            "ici transfer seq mismatch (header %s, payload "
                            "%s) — dropping mis-paired payload",
                            header.get("seq"), seq,
                        )
                        self._mark_dropped(header["request_id"],
                                           header.get("trace_id"),
                                           backend="ici",
                                           reason="seq_mismatch")
                        continue
                    if not self.authorize(header["request_id"], ids):
                        self._mark_dropped(header["request_id"],
                                           header.get("trace_id"),
                                           backend="ici",
                                           reason="unauthorized")
                        continue  # request gone — drop the received blocks
                    result = self.scatter(header["request_id"], ids, k, v)
                    if inspect.isawaitable(result):
                        await result
                elif mtype == "pull":
                    # read-only block serve (cluster KV fabric): stream
                    # the longest locally-resident run of the requested
                    # hash chain back over THIS connection
                    await self._serve_pull(header, writer)
                elif mtype == "commit":
                    rid = header["request_id"]
                    streaming.discard(rid)
                    if self._poison.pop(rid):
                        # a payload frame for this request was dropped —
                        # its KV blocks were never (fully) scattered, so
                        # committing would resume decode over garbage.
                        # Nack: the sender releases its side, the decode
                        # side's pending future times out and the request
                        # re-prefills locally.
                        logger.warning(
                            "nacking commit for %s: an earlier payload "
                            "frame was dropped", rid,
                        )
                        writer.write(struct.pack(">I", 1) + b"\x00")
                        await writer.drain()
                        continue
                    top = header.get("top")
                    self.on_commit(
                        rid, header["first_token"],
                        header.get("logprob"),
                        {int(k): float(v) for k, v in top.items()}
                        if top else None,
                        header.get("spans"),
                    )
                    # ack the commit so the sender can safely release blocks
                    writer.write(struct.pack(">I", 1) + b"\x01")
                    await writer.drain()
                else:
                    logger.error("unknown transfer frame type %r", mtype)
                    return
        except Exception:
            logger.exception("kv transfer connection failed")
        finally:
            for rid in streaming:
                logger.warning(
                    "transfer connection closed mid-stream for %s; "
                    "poisoning its commit (decode will fall back to "
                    "local prefill)", rid,
                )
                self._mark_dropped(rid, reason="conn_death")
            writer.close()

    async def _serve_pull(self, header: dict,
                          writer: asyncio.StreamWriter) -> None:
        """Serve one ``pull`` frame: resolve the longest locally-held
        run of the requested sequence-hash chain and stream it back as
        chunk frames + a ``pull_end`` trailer.

        Strictly read-only: blocks are pinned for the duration (the
        grant), and unpinned in the ``finally`` — a puller that
        vanishes mid-stream costs this engine nothing but the frames
        already sent. The tcp path gathers and byte-packs off-loop; a
        negotiated ici pull keeps payloads on device — per chunk, a
        ``pull_ici_blocks`` control frame precedes one collective
        entry, and the next header is written only after that entry
        resolved (the one-in-flight pairing discipline).
        """
        from ..telemetry.flight import flight_recorder

        hashes = [int(h) for h in header.get("hashes") or []]
        chunk = max(1, int(header.get("chunk_blocks", 16)))
        use_ici = (header.get("backend") == "ici"
                   and self.ici_send is not None and self.ici_send.alive)
        grant = self.pull_source(hashes) if self.pull_source else None
        flight_recorder().record(
            "kv_fabric.serve", trace_id=header.get("trace_id"),
            asked=len(hashes), served=len(grant) if grant else 0,
            backend="ici" if use_ici else "tcp",
        )
        if grant is None:
            pack_frame(writer, {"type": "pull_end", "served": 0})
            await writer.drain()
            return
        try:
            n = len(grant)
            for lo in range(0, n, chunk):
                if maybe_drop_connection("fabric"):
                    # chaos site: the serving side dies mid-stream — the
                    # puller must fall back to local recompute with its
                    # reservation freed and nothing registered
                    writer.close()
                    return
                hi = min(lo + chunk, n)
                if use_ici:
                    k_dev, v_dev = await grant.gather_frame_device(lo, hi)
                    seq = self.ici_send.next_seq()
                    pack_frame(writer, {"type": "pull_ici_blocks",
                                        "nblocks": hi - lo, "seq": seq})
                    await writer.drain()
                    # one collective in flight; a failure classifies
                    # against the header just written (balance or
                    # abandon), and the closed connection tells the
                    # puller to fall back
                    await self.ici_send.send(k_dev, v_dev, seq, hi - lo)
                else:
                    kb, vb, shape, dtype = await grant.gather_frame(lo, hi)
                    pack_frame(writer, {
                        "type": "pull_blocks", "shape": shape,
                        "dtype": dtype,
                        "k_bytes": len(kb), "v_bytes": len(vb),
                    }, kb, vb)
                    await writer.drain()
            pack_frame(writer, {"type": "pull_end", "served": n})
            await writer.drain()
        finally:
            grant.release()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class KvTransferClient:
    """Prefill-side connection pushing block frames to one decode engine."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None

    async def connect(self) -> "KvTransferClient":
        self.reader, self.writer = await asyncio.open_connection(self.host, self.port)
        return self

    def _send_header(self, header: dict) -> None:
        pack_frame(self.writer, header)

    async def send_blocks(
        self,
        request_id: str,
        block_ids: List[int],
        k_blocks: np.ndarray,   # [L, n, bs, KVH, D]
        v_blocks: np.ndarray,
        chunk_blocks: int = 16,
        trace_id: Optional[str] = None,
    ) -> None:
        """Stream blocks in chunks so the receiver overlaps scatter w/ reads."""
        n = len(block_ids)
        assert k_blocks.shape[1] == n
        for i in range(0, n, chunk_blocks):
            if maybe_drop_connection("disagg"):
                # chaos site: the sender dies mid-stream — the receiver
                # must poison this request's commit (utils/faults.py)
                self.writer.close()
                raise ConnectionResetError(
                    "fault injected: transfer_conn_drop"
                )
            ids = block_ids[i : i + chunk_blocks]
            header = {
                "type": "blocks",
                "request_id": request_id,
                "block_ids": list(map(int, ids)),
            }
            if trace_id:
                header["trace_id"] = trace_id
            await TcpBackend.send_blocks(
                self.writer, header,
                k_blocks[:, i : i + len(ids)],
                v_blocks[:, i : i + len(ids)],
            )

    async def send_ici_blocks(
        self, request_id: str, block_ids: List[int], seq: int = 0,
        trace_id: Optional[str] = None,
    ) -> None:
        """Announce a collective-plane transfer: ids over TCP, bytes over
        ICI/DCN (the caller enters IciKvTransfer.send(..., seq=seq) after
        this drains; the receiver cross-checks seq against the payload)."""
        header = {
            "type": "ici_blocks",
            "request_id": request_id,
            "block_ids": list(map(int, block_ids)),
            "seq": int(seq),
        }
        if trace_id:
            header["trace_id"] = trace_id
        self._send_header(header)
        await self.writer.drain()

    async def send_commit(self, request_id: str, first_token: int,
                          logprob: Optional[float] = None,
                          top: Optional[dict] = None,
                          spans: Optional[dict] = None) -> bool:
        """Returns True if the receiver committed, False if it nacked
        (a payload frame was dropped — the decode side will re-prefill
        locally; the sender just releases its resources either way).
        ``spans`` piggybacks the sender's span export for the stitched
        trace — its wall-clock marks + recv/send timestamps."""
        self._send_header({
            "type": "commit",
            "request_id": request_id,
            "first_token": int(first_token),
            "logprob": None if logprob is None else float(logprob),
            # first-token top-logprob alternatives (string token-id keys
            # for the msgpack strict decode)
            "top": {str(k): float(v) for k, v in top.items()} if top else None,
            "spans": spans,
        })
        await self.writer.drain()
        # wait for the receiver's ack — after this the decode side owns the KV
        ack = await read_exact(self.reader, 5)
        return ack[-1:] == b"\x01"

    async def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            try:
                await self.writer.wait_closed()
            # dynlint: allow(silent-except) - best-effort close of a possibly-dead peer
            except Exception:
                pass


# retained import surface for callers predating the unified plane
# (kv/cold_tier.py dtype resolution); the implementations live in
# dynamo_tpu/transfer/framing.py now
_np_dtype = np_dtype
_read_exact = read_exact
__all__ = [
    "KvTransferClient", "KvTransferServer", "transfer_key", "MAX_HEADER",
]
