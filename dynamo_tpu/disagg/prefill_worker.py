"""Prefill worker: pops the queue, computes KV, pushes it to decode workers.

The reference's PrefillWorker (reference:
examples/llm/components/prefill_worker.py:50-181 — poll loop over the NATS
JetStream queue, NIXL metadata lookup in etcd, prefill with max_tokens=1,
RDMA write into the decode worker's blocks). Here: pop the dynstore work
queue, resolve the decode engine's transfer descriptor from discovery, run
the prefill as a CHUNKED pipeline on the local runner (the same shared
``build_prefill_arrays`` bucket ladder + ``max_prefill_tokens_per_step``
budget the decode scheduler's local chunked prefill uses), and stream each
chunk's completed KV blocks to the decode engine while the next chunk
computes on device — the reference's ``CopyStream::trigger_layer`` per-layer
overlap (disagg/transfer.py module docstring), lifted to per-chunk
granularity. Remote TTFT then approaches ``max(compute, transfer)`` instead
of their sum, and host memory is bounded at ≤2 chunk-sized frames instead
of scaling with prompt length. The queue item is acked only after the
commit is acknowledged — a crash anywhere earlier redelivers the work to
another prefill worker. One streaming-era nuance: if the crash happened
AFTER a frame shipped, the receiver conservatively poisons that request's
commit (it cannot prove a re-stream covered everything the dead
connection touched), so the redelivered attempt is nacked and the decode
side completes via local-prefill fallback; crashes before the first frame
redeliver-and-commit normally (docs/disagg_serving.md).

Pipeline shape (both transfer planes):

  chunk i compute ──▶ chunk i+1 compute ──▶ ...      (device, dispatch order)
        └▶ frame gather (device)  └▶ frame gather
               └▶ pack/host-sync + wire write        (pump: executor + socket)

The jitted frame gather is dispatched on the event loop, BETWEEN chunk
steps: the step donates the cache buffers, so every op touching
``runner.kv_cache`` must serialize on one thread, and device dispatch order
then pins each gather to read exactly the blocks its chunk completed. All
host syncs (device→host copy, byte packing) and frame writes ride the pump
off-loop — the executor-bound discipline dynlint's ``async-blocking`` rule
enforces.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Tuple

import jax
import msgpack
import numpy as np

from ..engine.block_allocator import BlockAllocator
from ..runtime.engine import AsyncEngineContext
from ..engine.sampling import seed_to_key
from ..engine.scheduler import build_prefill_arrays, prefill_bucket_cap
from ..telemetry.flight import flight_recorder
from ..telemetry.registry import MetricsRegistry
from ..tokens import compute_block_hashes
from ..transfer.ici import settle_collective_send
from ..transfer.plane import (
    FramePipe,
    TransferMetrics,
    negotiate_backend,
    record_open,
)
from .protocols import PrefillQueue, RemotePrefillRequest
from .transfer import KvTransferClient, transfer_key

logger = logging.getLogger(__name__)


class PrefillWorker:
    def __init__(
        self,
        drt,
        runner,
        config,
        namespace: str = "public",
        component: str = "backend",
        transfer_chunk_blocks: int = 16,
        ici=None,  # IciKvTransfer (sender role) → bytes ride ICI/DCN
    ):
        self.drt = drt
        self.runner = runner
        self.config = config
        self.namespace = namespace
        self.component = component
        self.transfer_chunk_blocks = transfer_chunk_blocks
        self.ici = ici
        self._ici_seq = 0
        self.queue = PrefillQueue(drt.messaging, namespace)
        self.allocator = BlockAllocator(
            config.num_kv_blocks, config.kv_block_size,
            config.enable_prefix_caching,
        )
        self.key = jax.random.PRNGKey(config.seed)
        self._clients: Dict[str, KvTransferClient] = {}
        self._stopping = False
        # telemetry — plain attributes kept for the ad-hoc metrics() dict
        # (and tests); the registry renders the same counts into the
        # /metrics exposition (cli.run run_prefill --metrics-port)
        self.prefills = 0
        self.prefill_tokens = 0
        self.transfer_bytes = 0
        self.transfer_frames = 0
        self.prefix_hit_tokens = 0
        self.prefix_total_tokens = 0
        self.max_live_host_frames = 0
        self.registry = MetricsRegistry()
        self._prefills_c = self.registry.counter(
            "dynamo_prefill_worker_prefills_total",
            "Remote prefills completed by this worker (committed or nacked)",
        )
        self._prefill_tokens_c = self.registry.counter(
            "dynamo_prefill_worker_prefill_tokens_total",
            "Prompt tokens actually computed (prefix-cache hits excluded)",
        )
        self._queue_wait_h = self.registry.histogram(
            "dynamo_prefill_worker_queue_wait_seconds",
            "Queue latency: decode-side enqueue → this worker's pop",
        )
        # the unified dynamo_transfer_* family (docs/transfer_plane.md),
        # labelled {plane=disagg, backend=tcp|ici} — replaces the retired
        # dynamo_prefill_worker_transfer_bytes_total and
        # dynamo_disagg_transfer_{duration,exposed}_seconds instruments
        self._xfer = TransferMetrics(self.registry, plane="disagg")
        self.registry.callback_gauge(
            "dynamo_prefill_worker_kv_active_blocks",
            "KV blocks held by in-flight prefills + this worker's prefix cache",
            # dynrace: domain(executor)
            lambda: self.allocator.used,
        )
        self.registry.callback_gauge(
            "dynamo_prefill_worker_prefix_hit_ratio",
            "Prompt tokens skipped via this worker's own prefix cache / "
            "total prompt tokens (mirror of the scheduler's "
            "dynamo_kv_prefix_hit_ratio)",
            # dynrace: domain(executor)
            lambda: (
                self.prefix_hit_tokens / self.prefix_total_tokens
                if self.prefix_total_tokens else 0.0
            ),
        )
        # the runner's XLA compile instruments render in this worker's
        # sidecar scrape too; the flight ring records engine events
        self.flight = flight_recorder()
        compiles = getattr(runner, "compiles", None)
        if compiles is not None:
            self.registry.attach(compiles.registry)

    # ---------- main loop ----------

    async def run(self) -> None:
        # compiles past this point stall queued prefills — tag them late
        compiles = getattr(self.runner, "compiles", None)
        if compiles is not None:
            compiles.mark_serving_started()
        while not self._stopping:
            if not await self.serve_one(timeout=1.0):
                continue

    def stop(self) -> None:
        self._stopping = True

    async def serve_one(self, timeout: Optional[float] = None) -> bool:
        """Pop and fully process one queue item. Returns False on timeout."""
        try:
            popped = await self.queue.pop(timeout=timeout)
        except Exception:
            # transient broker failure — back off; never crash run()
            logger.exception("prefill queue pop failed")
            await asyncio.sleep(1.0)
            return False
        if popped is None:
            return False
        rpr, ack = popped
        # per-request span context for the cluster-stitched trace: the
        # worker's marks (dequeue → compute → transfer) ship back on the
        # commit frame, stamped against THIS process's clock — the
        # decode side folds them with a queue-transit offset estimate
        ctx = AsyncEngineContext(trace_id=rpr.trace_id or rpr.request_id)
        ctx.add_stage("prefill.dequeue")
        if rpr.enqueued_at:
            # wall-clock across processes (same deployment host class);
            # clamp at 0 so skew never renders a negative wait
            self._queue_wait_h.observe(max(0.0, time.time() - rpr.enqueued_at))
        try:
            await self._handle(rpr, ctx)
        except Exception:
            # no ack — the visibility window redelivers this item
            logger.exception("prefill of %s (trace %s) failed; leaving for "
                             "redelivery", rpr.request_id,
                             rpr.trace_id or rpr.request_id)
            stale = self._clients.pop(rpr.engine_id, None)
            if stale is not None:
                self._xfer.channel_closed(
                    getattr(stale, "plane_backend", "tcp"))
                await stale.close()
            return True
        ack()
        return True

    # ---------- the work ----------

    def _chunk_cap(self) -> int:
        """The shared single-row bucket cap (engine/scheduler.py
        prefill_bucket_cap — the same derivation the decode scheduler's
        chunked prefill uses), floored at the smallest bucket: one chunk
        must still advance or the prefill livelocks."""
        cap = prefill_bucket_cap(self.config)
        return cap if cap is not None else self.config.prefill_buckets[0]

    async def _handle(self, rpr: RemotePrefillRequest,
                      ctx: AsyncEngineContext) -> None:
        # ctx is required: the caller stamps "prefill.dequeue" BEFORE
        # calling, and the span export takes stages[0] as the hop's
        # recv_at — a ctx built here would make that the compute-done
        # mark and inflate the hop's estimated rtt by the whole prefill
        cfg = self.config
        bs = cfg.kv_block_size
        prompt = rpr.token_ids
        loop = asyncio.get_running_loop()

        block_ids, num_cached = self.allocator.allocate_prompt(prompt)
        pipe: Optional[FramePipe] = None
        try:
            client = await self._client(rpr.engine_id)
            use_ici = self.ici is not None and self._ici_usable(client)
            backend = "ici" if use_ici else "tcp"

            if rpr.seed is not None:
                # same key derivation as the decode scheduler's local path:
                # fold_in(seed_key, generated=0) — bit-identical first token
                seed_keys = seed_to_key(int(rpr.seed))[None, :]
            else:
                self.key, step_key = jax.random.split(self.key)
                seed_keys = np.asarray(
                    jax.random.key_data(step_key), np.uint32)[None, :]
            # sampling state: prompt presence for repetition penalty plus
            # the request's logit_bias (slot 0 of this worker's runner)
            self.runner.set_sample_row(
                0, prompt, [], logit_bias=rpr.logit_bias
            )
            samp_args = (
                np.asarray([rpr.temperature], np.float32),
                np.asarray([rpr.top_k], np.int32),
                np.asarray([rpr.top_p], np.float32),
            )
            samp_kw = dict(
                min_p=np.asarray([rpr.min_p], np.float32),
                presence_penalty=np.asarray([rpr.presence_penalty], np.float32),
                frequency_penalty=np.asarray([rpr.frequency_penalty], np.float32),
                repetition_penalty=np.asarray([rpr.repetition_penalty], np.float32),
                seed_keys=seed_keys,
                counters=np.zeros(1, np.int32),
                sample_slots=np.zeros(1, np.int32),
            )

            # long-context admission class (docs/long_context.md): when
            # this worker carries a sequence-parallel mesh and the
            # uncached suffix crosses the threshold, the SAME chunk
            # ladder runs through the SP program — each chunk is
            # mesh-wide (sp × the dense budget) and the streaming plane
            # below is untouched: the SP program scatters into the same
            # paged cache the frame gathers read
            use_sp = (
                getattr(self.runner, "sp_ready", False)
                and cfg.long_prefill_threshold_tokens > 0
                and len(prompt) - num_cached
                >= cfg.long_prefill_threshold_tokens
            )
            # stream plan: the decode side already holds blocks below
            # first_block; everything from there ships as bounded frames,
            # each as soon as its last position's KV is scheduled
            first_block = rpr.num_cached // bs
            limit = len(block_ids)
            cap = self.runner.sp_chunk_tokens if use_sp \
                else self._chunk_cap()
            frame_blocks = (
                self.ici.buckets[-1] if use_ici else max(1, cap // bs)
            )
            pipe = self._start_pump(client, rpr, use_ici, frame_blocks)

            shipped = first_block
            # worker-side prefix-cache hits are complete KV from the start:
            # ship them immediately so their transfer overlaps chunk 1
            cached_ready = min(num_cached // bs, limit)
            if cached_ready > shipped:
                await self._ship(pipe, rpr, block_ids, shipped, cached_ready)
                shipped = cached_ready

            outs = None
            pos, total = num_cached, len(prompt)
            while True:
                end = min(pos + cap, total)
                final = end >= total
                # dispatch-only either way: JAX queues the chunk; the one
                # host sync happens once, on the final chunk's outputs
                if use_sp:
                    outs = self.runner.sp_prefill_chunk(
                        prompt[:end], pos, block_ids,
                        temperature=rpr.temperature, top_k=rpr.top_k,
                        top_p=rpr.top_p, min_p=rpr.min_p,
                        presence_penalty=rpr.presence_penalty,
                        frequency_penalty=rpr.frequency_penalty,
                        repetition_penalty=rpr.repetition_penalty,
                        seed_keys=samp_kw["seed_keys"][0],
                        counters=0, sample_slot=0, commit=final,
                        want_top=final and rpr.logprobs_n > 0,
                    )
                else:
                    arrays = build_prefill_arrays(
                        cfg, prompt[:end], pos, block_ids)
                    outs = self.runner.step(
                        *arrays, *samp_args, **samp_kw,
                        # alternatives only when the request asked for
                        # top_logprobs, and only on the chunk that
                        # samples (same gate as the decode scheduler)
                        want_top=final and rpr.logprobs_n > 0,
                    )
                ready = limit if final else min(end // bs, limit)
                if ready > shipped:
                    await self._ship(pipe, rpr, block_ids, shipped, ready)
                    shipped = ready
                pos = end
                if final:
                    break

            next_tokens, lps, top_vals, top_ids, *_ = outs
            token, lp, top = await loop.run_in_executor(
                None,
                lambda: (
                    int(np.asarray(next_tokens)[0]),
                    float(np.asarray(lps)[0]),
                    {
                        int(t): float(v)
                        for t, v in zip(
                            np.asarray(top_ids)[0], np.asarray(top_vals)[0]
                        )
                    } if rpr.logprobs_n > 0 else None,
                ),
            )
            t_compute_done = time.monotonic()
            # closing-mark semantics: the span from dequeue to here is
            # the chunked prefill compute (final-chunk host sync incl.)
            ctx.add_stage("prefill.compute")

            # feed the local prefix cache so future prompts skip this work
            hashes = compute_block_hashes(prompt, bs)
            parent = None
            for i, h in enumerate(hashes):
                self.allocator.register_complete(block_ids[i], h, parent)
                parent = h

            nbytes = await pipe.drain()
            # every frame is on the wire: the transfer tail that did NOT
            # hide behind compute closes here (the stitched-trace twin of
            # dynamo_transfer_exposed_seconds{plane="disagg"})
            ctx.add_stage("prefill.transfer")
            committed = await client.send_commit(
                rpr.request_id, token, lp if rpr.want_logprobs else None,
                top=top,
                spans={
                    "source": "prefill_worker",
                    "spans": ctx.export_spans(),
                    # offset-estimation pair: rpr.enqueued_at is the
                    # decode side's send stamp; these two are ours
                    "recv_at": ctx.wall(ctx.stages[0][1]),
                    "resp_sent_at": time.time(),
                },
            )
            t_done = time.monotonic()
            if pipe.first_frame_t is not None:
                self._xfer.observe_duration(
                    t_done - pipe.first_frame_t, backend)
                self._xfer.observe_exposed(
                    max(0.0, t_done - t_compute_done), backend)
            if not committed:
                # the receiver dropped a payload frame and nacked — the
                # decode side re-prefills locally after its timeout. Work
                # is done from this worker's perspective (ack the queue
                # item; a redelivery would nack again: the request id
                # stays revoked on the decode side).
                self.flight.record(
                    "disagg.nack", request_id=rpr.request_id,
                    trace_id=rpr.trace_id or None,
                )
                logger.warning(
                    "decode engine nacked commit for %s (dropped payload); "
                    "it will fall back to local prefill", rpr.request_id,
                )
            self.prefills += 1
            self.prefill_tokens += len(prompt) - num_cached
            self.transfer_bytes += nbytes
            self.transfer_frames += pipe.frames
            self.prefix_hit_tokens += num_cached
            self.prefix_total_tokens += len(prompt)
            self.max_live_host_frames = max(
                self.max_live_host_frames, pipe.max_live_host_frames
            )
            self._prefills_c.inc()
            self._prefill_tokens_c.inc(len(prompt) - num_cached)
            self._xfer.add_bytes(nbytes, backend)
        finally:
            if pipe is not None:
                await pipe.shutdown()
            self.allocator.free_blocks(block_ids)

    # ---------- the frame stream ----------

    def _start_pump(self, client, rpr, use_ici: bool,
                    frame_blocks: int) -> FramePipe:
        pipe = FramePipe(
            depth=getattr(self.config, "disagg_stream_depth", 2),
            frame_blocks=frame_blocks,
        )
        pump = self._ici_pump if use_ici else self._tcp_pump
        pipe.task = asyncio.ensure_future(self._run_pump(pipe, pump, client, rpr))
        return pipe

    async def _run_pump(self, pipe: FramePipe, pump, client, rpr) -> None:
        try:
            await pump(pipe, client, rpr)
        except asyncio.CancelledError:
            # shutdown() cancelling this task — do NOT enter the consume
            # loop: a caught cancellation is not re-delivered, so waiting
            # on the queue here would block forever (nothing will feed it;
            # the producer is the one tearing us down)
            raise
        # dynlint: allow(silent-except) - not swallowed: stored in pipe.error, re-raised by drain()/put()
        except BaseException as e:
            pipe.error = e
            # keep consuming so a producer blocked on the bounded queue
            # wakes up (it re-checks pipe.error after every put); stop at
            # the sentinel — and skip entirely if the pump already saw it
            while not pipe.closed:
                if await pipe.q.get() is None:
                    pipe.closed = True

    async def _ship(self, pipe: FramePipe, rpr, block_ids,
                    lo: int, hi: int) -> None:
        """Dispatch the device gather for blocks [lo, hi) and enqueue the
        frames. Runs on the event loop by design: the gather must
        serialize with the chunk steps (the step donates the cache
        buffers it replaces), and loop-side dispatch order pins the read
        between the chunk that completed these blocks and the next."""
        step = pipe.frame_blocks
        for i in range(lo, hi, step):
            src = block_ids[i : min(i + step, hi)]
            dst = rpr.block_ids[i : min(i + step, hi)]
            k_dev, v_dev = self.runner.gather_blocks_device(src)
            await pipe.put((k_dev, v_dev, dst))

    async def _tcp_pump(self, pipe: FramePipe, client, rpr) -> None:
        """TCP plane: per frame, host-sync the gathered blocks in an
        executor, then write the frame; with depth 2 the next frame's
        host copy proceeds while the previous frame's bytes drain."""
        loop = asyncio.get_running_loop()
        prev_send: Optional[asyncio.Task] = None

        async def send(k: np.ndarray, v: np.ndarray, dst: List[int]) -> None:
            try:
                await client.send_blocks(
                    rpr.request_id, dst, k, v,
                    chunk_blocks=self.transfer_chunk_blocks,
                    trace_id=rpr.trace_id or None,
                )
                pipe.nbytes += k.nbytes + v.nbytes
            finally:
                pipe.live_host_frames -= 1

        try:
            while True:
                frame = await pipe.q.get()
                if frame is None:
                    pipe.closed = True
                    break
                k_dev, v_dev, dst = frame
                k, v = await loop.run_in_executor(
                    None,
                    lambda a=k_dev, b=v_dev: self.runner.blocks_to_host(a, b),
                )
                pipe.frames += 1
                pipe.live_host_frames += 1
                pipe.max_live_host_frames = max(
                    pipe.max_live_host_frames, pipe.live_host_frames
                )
                if prev_send is not None:
                    await prev_send
                    prev_send = None
                if pipe.depth >= 2:
                    prev_send = asyncio.ensure_future(send(k, v, dst))
                else:
                    await send(k, v, dst)
            if prev_send is not None:
                await prev_send
                prev_send = None
        finally:
            if prev_send is not None:
                prev_send.cancel()
                try:
                    await prev_send
                # dynlint: allow(silent-except) - cancel-join of the in-flight frame write on the error path; the primary error is already propagating
                except BaseException:
                    pass

    async def _ici_pump(self, pipe: FramePipe, client, rpr) -> None:
        """Collective plane: ids over TCP (ordering), bytes HBM→HBM.

        Pipelined but discipline-preserving: at most ONE collective is in
        flight, and frame i+1's header is written only after frame i's
        collective resolved — a failure therefore always classifies
        against the LAST header sent, so the poison-balancing rules
        (pre-entry → balance and keep the plane; entered/unknowable →
        abandon) apply exactly as in the serial loop. The overlap comes
        from the chunk loop: the next frame's device gather (and the next
        chunk's compute) dispatch while this frame's bytes are on the
        interconnect.
        """
        loop = asyncio.get_running_loop()
        prev: Optional[Tuple] = None  # (executor future, ndst, nbytes)

        async def finish_prev():
            # clear BEFORE awaiting: a failed finish must never be
            # re-awaited by the finally below — its classification
            # (balancing entry / plane abandonment) already ran, and
            # running it twice would itself unbalance the plane
            nonlocal prev
            p, prev = prev, None
            await self._finish_ici_send(loop, pipe, p)

        try:
            while True:
                frame = await pipe.q.get()
                if frame is None:
                    pipe.closed = True
                    break
                k_dev, v_dev, dst = frame
                if prev is not None:
                    await finish_prev()
                self._ici_seq += 1
                seq = self._ici_seq
                try:
                    await client.send_ici_blocks(
                        rpr.request_id, dst, seq,
                        trace_id=rpr.trace_id or None,
                    )
                except BaseException:
                    # header delivery unknowable → pairing discipline
                    # unknowable → abandon the plane (tcp from now on);
                    # the receiver's seq check drops any leftover
                    logger.exception(
                        "ici header send failed; abandoning the "
                        "collective plane (tcp fallback)"
                    )
                    self.ici = None
                    raise
                pipe.frames += 1
                fut = loop.run_in_executor(
                    None, lambda a=k_dev, b=v_dev, s=seq: self.ici.send(a, b, s)
                )
                prev = (fut, len(dst), int(k_dev.nbytes) + int(v_dev.nbytes))
                if pipe.depth < 2:
                    await finish_prev()
            if prev is not None:
                await finish_prev()
        finally:
            if prev is not None:
                # error/cancel path with a collective still in flight:
                # join and classify it so the plane's pairing discipline
                # (balancing entry or abandonment) runs instead of the
                # future being abandoned with an unpaired receiver entry
                try:
                    await finish_prev()
                # dynlint: allow(silent-except) - classification/balancing already ran inside; the primary error is propagating
                except BaseException:
                    pass

    async def _finish_ici_send(self, loop, pipe: FramePipe, prev) -> None:
        # the pairing discipline (pre-entry → balance and keep; entered/
        # unknowable → abandon) lives in the unified transfer plane; the
        # plane object here stays the raw IciKvTransfer and abandonment
        # keeps its ici=None convention (negotiation then yields tcp)
        fut, ndst, nbytes = prev
        plane = self.ici

        def abandon():
            self.ici = None

        await settle_collective_send(loop, plane, fut, ndst, abandon)
        pipe.nbytes += nbytes

    def _ici_usable(self, client) -> bool:
        """The collective plane applies only when the TARGET engine is this
        plane's configured receiver — another ici-enabled engine would
        enter a DIFFERENT mesh and both sides would hang unpaired.
        Delegates to the unified plane's per-peer negotiation."""
        return negotiate_backend(
            {
                "modes": getattr(client, "modes", ("tcp",)),
                "ici_rank": getattr(client, "ici_rank", None),
            },
            self.ici, peer_role="receiver",
        ) == "ici"

    async def _client(self, engine_id: str) -> KvTransferClient:
        client = self._clients.get(engine_id)
        if client is not None:
            return client
        raw = await self.drt.discovery.kv_get(
            transfer_key(self.namespace, self.component, engine_id)
        )
        if raw is None:
            raise ConnectionError(f"no kv transfer descriptor for {engine_id}")
        desc = msgpack.unpackb(raw, raw=False)
        client = await KvTransferClient(desc["host"], desc["port"]).connect()
        # payload paths BOTH ends support (older descriptors: tcp only)
        client.modes = tuple(desc.get("modes", ("tcp",)))
        client.ici_rank = desc.get("ici_rank")
        # channel lifecycle with backend attribution: the negotiated
        # payload path at dial time (abandonment later just reroutes
        # transfers to tcp on the same channel)
        client.plane_backend = (
            "ici" if self.ici is not None and self._ici_usable(client)
            else "tcp"
        )
        record_open("disagg", client.plane_backend, peer=engine_id)
        self._xfer.channel_opened(client.plane_backend)
        self._clients[engine_id] = client
        return client

    def metrics(self) -> dict:
        return {
            "prefills_total": self.prefills,
            "prefill_tokens_total": self.prefill_tokens,
            "transfer_bytes_total": self.transfer_bytes,
            "kv_active_blocks": self.allocator.used,
            "kv_total_blocks": self.allocator.num_blocks,
        }

    async def close(self) -> None:
        self.stop()
        for client in self._clients.values():
            self._xfer.channel_closed(
                getattr(client, "plane_backend", "tcp"))
            await client.close()
        self._clients.clear()
