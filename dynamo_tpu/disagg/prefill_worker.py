"""Prefill worker: pops the queue, computes KV, pushes it to decode workers.

The reference's PrefillWorker (reference:
examples/llm/components/prefill_worker.py:50-181 — poll loop over the NATS
JetStream queue, NIXL metadata lookup in etcd, prefill with max_tokens=1,
RDMA write into the decode worker's blocks). Here: pop the dynstore work
queue, resolve the decode engine's transfer descriptor from discovery, run
one bucketed prefill step on the local runner (using the worker's *own*
prefix cache to skip recomputation), gather the needed blocks from HBM and
stream them to the decode engine, then commit the sampled first token.
The queue item is acked only after the commit is acknowledged — a crash
anywhere earlier redelivers the work to another prefill worker.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, Optional, Tuple

import jax
import msgpack
import numpy as np

from ..engine.block_allocator import BlockAllocator
from ..engine.sampling import seed_to_key
from ..engine.scheduler import build_prefill_arrays
from ..tokens import compute_block_hashes
from .protocols import PrefillQueue, RemotePrefillRequest
from .transfer import KvTransferClient, transfer_key

logger = logging.getLogger(__name__)


class PrefillWorker:
    def __init__(
        self,
        drt,
        runner,
        config,
        namespace: str = "public",
        component: str = "backend",
        transfer_chunk_blocks: int = 16,
        ici=None,  # IciKvTransfer (sender role) → bytes ride ICI/DCN
    ):
        self.drt = drt
        self.runner = runner
        self.config = config
        self.namespace = namespace
        self.component = component
        self.transfer_chunk_blocks = transfer_chunk_blocks
        self.ici = ici
        self._ici_seq = 0
        self.queue = PrefillQueue(drt.messaging, namespace)
        self.allocator = BlockAllocator(
            config.num_kv_blocks, config.kv_block_size,
            config.enable_prefix_caching,
        )
        self.key = jax.random.PRNGKey(config.seed)
        self._clients: Dict[str, KvTransferClient] = {}
        self._stopping = False
        # telemetry
        self.prefills = 0
        self.prefill_tokens = 0
        self.transfer_bytes = 0

    # ---------- main loop ----------

    async def run(self) -> None:
        while not self._stopping:
            if not await self.serve_one(timeout=1.0):
                continue

    def stop(self) -> None:
        self._stopping = True

    async def serve_one(self, timeout: Optional[float] = None) -> bool:
        """Pop and fully process one queue item. Returns False on timeout."""
        try:
            popped = await self.queue.pop(timeout=timeout)
        except Exception:
            # transient broker failure — back off; never crash run()
            logger.exception("prefill queue pop failed")
            await asyncio.sleep(1.0)
            return False
        if popped is None:
            return False
        rpr, ack = popped
        try:
            await self._handle(rpr)
        except Exception:
            # no ack — the visibility window redelivers this item
            logger.exception("prefill of %s (trace %s) failed; leaving for "
                             "redelivery", rpr.request_id,
                             rpr.trace_id or rpr.request_id)
            stale = self._clients.pop(rpr.engine_id, None)
            if stale is not None:
                await stale.close()
            return True
        ack()
        return True

    # ---------- the work ----------

    async def _handle(self, rpr: RemotePrefillRequest) -> None:
        cfg = self.config
        bs = cfg.kv_block_size
        prompt = rpr.token_ids
        loop = asyncio.get_running_loop()

        block_ids, num_cached = self.allocator.allocate_prompt(prompt)
        try:
            arrays = build_prefill_arrays(cfg, prompt, num_cached, block_ids)
            if rpr.seed is not None:
                # same key derivation as the decode scheduler's local path:
                # fold_in(seed_key, generated=0) — bit-identical first token
                seed_keys = seed_to_key(int(rpr.seed))[None, :]
            else:
                self.key, step_key = jax.random.split(self.key)
                seed_keys = np.asarray(
                    jax.random.key_data(step_key), np.uint32)[None, :]
            # sampling state: prompt presence for repetition penalty plus
            # the request's logit_bias (slot 0 of this worker's runner)
            self.runner.set_sample_row(
                0, prompt, [], logit_bias=rpr.logit_bias
            )
            next_tokens, lps, top_vals, top_ids, *_ = self.runner.step(
                *arrays,
                np.asarray([rpr.temperature], np.float32),
                np.asarray([rpr.top_k], np.int32),
                np.asarray([rpr.top_p], np.float32),
                min_p=np.asarray([rpr.min_p], np.float32),
                presence_penalty=np.asarray([rpr.presence_penalty], np.float32),
                frequency_penalty=np.asarray([rpr.frequency_penalty], np.float32),
                repetition_penalty=np.asarray([rpr.repetition_penalty], np.float32),
                seed_keys=seed_keys,
                counters=np.zeros(1, np.int32),
                sample_slots=np.zeros(1, np.int32),
                # alternatives only when the request asked for top_logprobs
                # (logprobs=0 means chosen-token logprob only — skip the
                # [B, V] top-k sort, same gate as the decode scheduler)
                want_top=rpr.logprobs_n > 0,
            )
            token, lp, top = await loop.run_in_executor(
                None,
                lambda: (
                    int(np.asarray(next_tokens)[0]),
                    float(np.asarray(lps)[0]),
                    {
                        int(t): float(v)
                        for t, v in zip(
                            np.asarray(top_ids)[0], np.asarray(top_vals)[0]
                        )
                    } if rpr.logprobs_n > 0 else None,
                ),
            )

            # feed the local prefix cache so future prompts skip this work
            hashes = compute_block_hashes(prompt, bs)
            parent = None
            for i, h in enumerate(hashes):
                self.allocator.register_complete(block_ids[i], h, parent)
                parent = h

            # gather + push the blocks the decode side doesn't already have
            first_block = rpr.num_cached // bs
            src_ids = block_ids[first_block:]
            dst_ids = rpr.block_ids[first_block : len(block_ids)]
            client = await self._client(rpr.engine_id)
            use_ici = self.ici is not None and self._ici_usable(client)
            nbytes = 0
            if use_ici:
                # collective plane: ids over TCP (ordering), bytes HBM→HBM;
                # chunk at the top transfer bucket — sender and receiver
                # must enter identically-shaped programs
                from .ici_transfer import IciSendError

                chunk = self.ici.buckets[-1]
                for i in range(0, len(src_ids), chunk):
                    src = src_ids[i : i + chunk]
                    dst = dst_ids[i : i + chunk]
                    # gather precedes the header: a gather failure leaves
                    # the plane balanced (no unpaired receiver entry)
                    k, v = await loop.run_in_executor(
                        None,
                        lambda s=src: self.runner.gather_blocks_device(s),
                    )
                    self._ici_seq += 1
                    seq = self._ici_seq
                    try:
                        await client.send_ici_blocks(rpr.request_id, dst, seq)
                    except BaseException:
                        # header delivery unknowable → pairing discipline
                        # unknowable → abandon the plane (tcp from now on);
                        # the receiver's seq check drops any leftover
                        logger.exception(
                            "ici header send failed; abandoning the "
                            "collective plane (tcp fallback)"
                        )
                        self.ici = None
                        raise
                    try:
                        await loop.run_in_executor(
                            None, lambda a=k, b=v, s=seq: self.ici.send(a, b, s)
                        )
                    except IciSendError as e:
                        if not e.entered:
                            # receiver holds an unpaired entry for this
                            # header — pair it with a poison payload (seq
                            # -1 never matches) so the plane stays 1:1 and
                            # REMAINS usable for the redelivery
                            try:
                                await loop.run_in_executor(
                                    None,
                                    lambda n=len(dst):
                                        self.ici.send_balancing_entry(n),
                                )
                                logger.warning(
                                    "ici send failed before entering the "
                                    "collective; balanced the plane and "
                                    "keeping it"
                                )
                            except BaseException:
                                logger.exception(
                                    "balancing entry failed; abandoning "
                                    "the collective plane (tcp fallback)"
                                )
                                self.ici = None
                        else:
                            # the collective itself failed — both sides'
                            # entries unwound, but the distributed runtime
                            # is now suspect
                            logger.exception(
                                "ici collective failed; abandoning the "
                                "plane (tcp fallback)"
                            )
                            self.ici = None
                        raise
                    nbytes += k.nbytes + v.nbytes
            else:
                k, v = await loop.run_in_executor(
                    None, lambda: self.runner.gather_blocks(src_ids)
                )
                await client.send_blocks(
                    rpr.request_id, dst_ids, k, v,
                    chunk_blocks=self.transfer_chunk_blocks,
                )
                nbytes = k.nbytes + v.nbytes
            committed = await client.send_commit(
                rpr.request_id, token, lp if rpr.want_logprobs else None,
                top=top,
            )
            if not committed:
                # the receiver dropped a payload frame and nacked — the
                # decode side re-prefills locally after its timeout. Work
                # is done from this worker's perspective (ack the queue
                # item; a redelivery would nack again: the request id
                # stays revoked on the decode side).
                logger.warning(
                    "decode engine nacked commit for %s (dropped payload); "
                    "it will fall back to local prefill", rpr.request_id,
                )
            self.prefills += 1
            self.prefill_tokens += len(prompt) - num_cached
            self.transfer_bytes += nbytes
        finally:
            self.allocator.free_blocks(block_ids)

    def _ici_usable(self, client) -> bool:
        """The collective plane applies only when the TARGET engine is this
        plane's configured receiver — another ici-enabled engine would
        enter a DIFFERENT mesh and both sides would hang unpaired."""
        modes = getattr(client, "modes", ("tcp",))
        if "ici" not in modes:
            logger.warning(
                "transfer server has no ici mode; using tcp for this engine"
            )
            return False
        rank = getattr(client, "ici_rank", None)
        # rank None = descriptor predates rank advertisement — trust the
        # mode flag (matches pre-rank behavior; a genuine mismatch is only
        # detectable when the receiver says who it is)
        if rank is not None and rank != self.ici.receiver_rank:
            logger.warning(
                "engine's ici receiver rank %s != configured %s; using tcp",
                rank, self.ici.receiver_rank,
            )
            return False
        return True

    async def _client(self, engine_id: str) -> KvTransferClient:
        client = self._clients.get(engine_id)
        if client is not None:
            return client
        raw = await self.drt.discovery.kv_get(
            transfer_key(self.namespace, self.component, engine_id)
        )
        if raw is None:
            raise ConnectionError(f"no kv transfer descriptor for {engine_id}")
        desc = msgpack.unpackb(raw, raw=False)
        client = await KvTransferClient(desc["host"], desc["port"]).connect()
        # payload paths BOTH ends support (older descriptors: tcp only)
        client.modes = tuple(desc.get("modes", ("tcp",)))
        client.ici_rank = desc.get("ici_rank")
        self._clients[engine_id] = client
        return client

    def metrics(self) -> dict:
        return {
            "prefills_total": self.prefills,
            "prefill_tokens_total": self.prefill_tokens,
            "transfer_bytes_total": self.transfer_bytes,
            "kv_active_blocks": self.allocator.used,
            "kv_total_blocks": self.allocator.num_blocks,
        }

    async def close(self) -> None:
        self.stop()
        for client in self._clients.values():
            await client.close()
        self._clients.clear()
