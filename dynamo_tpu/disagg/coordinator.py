"""Decode-side remote-prefill coordination.

Owns what the reference's VllmWorker did around its engine (reference:
examples/llm/components/worker.py:176-225): decide local-vs-remote per
request (conditional disagg + queue-depth feedback), allocate the KV blocks,
enqueue a RemotePrefillRequest, and hand the scheduler a future that
resolves when the prefill worker has pushed the blocks and committed the
first token.
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid
from typing import Dict, Optional, Sequence

import msgpack
import numpy as np

from ..telemetry.flight import flight_recorder
from ..telemetry.registry import MetricsRegistry
from .protocols import PrefillQueue, RemotePrefillRequest
from .router import DisaggRouter
from .transfer import KvTransferServer, transfer_key

logger = logging.getLogger(__name__)


class RemotePrefillCoordinator:
    """Lives inside a decode worker; one per engine."""

    def __init__(
        self,
        drt,
        runner,
        namespace: str = "public",
        component: str = "backend",
        router: Optional[DisaggRouter] = None,
        engine_id: Optional[str] = None,
        advertise_host: str = "127.0.0.1",
        depth_refresh_s: float = 0.25,
        prefill_timeout_s: float = 120.0,
        ici=None,  # IciKvTransfer (receiver role) → bytes ride ICI/DCN
    ):
        self.drt = drt
        self.runner = runner
        self.namespace = namespace
        self.component = component
        self.router = router or DisaggRouter(namespace=namespace)
        self.engine_id = engine_id or f"eng-{uuid.uuid4().hex[:12]}"
        self.queue = PrefillQueue(drt.messaging, namespace)
        self.prefill_timeout_s = prefill_timeout_s
        self._server = KvTransferServer(
            scatter=self._scatter,
            on_commit=self._commit,
            authorize=self._authorize,
            host=advertise_host,
            ici_recv=None if ici is None else ici.recv,
            ici_rank=None if ici is None else ici.receiver_rank,
        )
        self._pending: Dict[str, asyncio.Future] = {}
        # request id → AsyncEngineContext, for the kv_transfer stage mark
        self._ctx: Dict[str, object] = {}
        self._queue_depth = 0
        self._depth_refresh_s = depth_refresh_s
        self._depth_task: Optional[asyncio.Task] = None
        # telemetry — the registry is attached to the scheduler's, so
        # these render in the engine's unified /metrics exposition
        self.remote_submitted = 0
        self.remote_completed = 0
        self._submit_t: Dict[str, float] = {}  # request id → submit time
        # request id → wall-clock at submit: the local half of the
        # per-hop clock-offset estimate when the prefill worker's span
        # export arrives on the commit frame (telemetry/stitch.py)
        self._submit_wall: Dict[str, float] = {}
        self.registry = MetricsRegistry()
        self._rtt_hist = self.registry.histogram(
            "dynamo_disagg_remote_prefill_duration_seconds",
            "Remote prefill round trip: queue submit → first-token commit",
        )
        self._failures = self.registry.counter(
            "dynamo_disagg_remote_prefill_failures_total",
            "Remote prefills that never committed, by reason="
            "submit|timeout|cancelled",
        )
        self.registry.callback_gauge(
            "dynamo_disagg_pending_requests",
            "Remote prefills submitted and not yet committed",
            # dynrace: domain(executor)
            lambda: len(self._pending),
        )
        self.registry.callback_gauge(
            "dynamo_disagg_queue_depth_requests",
            "Prefill work-queue depth (cached; refreshed periodically)",
            # dynrace: domain(executor)
            lambda: self._queue_depth,
        )

    # ---------- lifecycle ----------

    async def start(self) -> "RemotePrefillCoordinator":
        await self._server.start()
        await self.router.start(self.drt.discovery, self.drt.runtime)
        lease = await self.drt.discovery.primary_lease()
        await self.drt.discovery.kv_put(
            transfer_key(self.namespace, self.component, self.engine_id),
            msgpack.packb(self._server.descriptor, use_bin_type=True),
            lease_id=lease.id,
        )
        self._depth_task = self.drt.runtime.spawn(self._depth_loop())
        return self

    async def close(self) -> None:
        if self._depth_task:
            self._depth_task.cancel()
        await self.router.stop()
        await self._server.close()

    async def _depth_loop(self) -> None:
        while True:
            try:
                self._queue_depth = await self.queue.depth()
            except Exception:
                logger.debug("queue depth refresh failed", exc_info=True)
            await asyncio.sleep(self._depth_refresh_s)

    # ---------- scheduler-facing API ----------

    def decide(self, prompt_len: int, prefix_hit_len: int) -> bool:
        """Should this prompt's prefill go remote? (sync; cached depth)"""
        return self.router.prefill_remote(
            prompt_len, prefix_hit_len, self._queue_depth
        )

    async def submit(self, request_id: str, token_ids: Sequence[int],
                     block_ids: Sequence[int], num_cached: int,
                     temperature: float = 0.0, top_k: int = 0,
                     top_p: float = 1.0, min_p: float = 0.0,
                     presence_penalty: float = 0.0,
                     frequency_penalty: float = 0.0,
                     repetition_penalty: float = 1.0,
                     seed: Optional[int] = None,
                     want_logprobs: bool = False,
                     logprobs_n: int = 0,
                     logit_bias: Optional[dict] = None,
                     trace_id: str = "", ctx=None) -> asyncio.Future:
        """Enqueue the prompt; returns a future → (first_token, logprob).

        ``ctx`` (the request's AsyncEngineContext, optional) gets a
        ``kv_transfer`` stage mark stamped when the commit lands, so the
        trace attributes the remote compute+transfer span distinctly from
        the scheduler's install latency."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[request_id] = fut
        if ctx is not None:
            self._ctx[request_id] = ctx
        try:
            await self.queue.push(RemotePrefillRequest(
                request_id=request_id,
                engine_id=self.engine_id,
                token_ids=list(map(int, token_ids)),
                block_ids=list(map(int, block_ids)),
                num_cached=num_cached,
                temperature=temperature, top_k=top_k, top_p=top_p,
                min_p=min_p, presence_penalty=presence_penalty,
                frequency_penalty=frequency_penalty,
                repetition_penalty=repetition_penalty, seed=seed,
                want_logprobs=want_logprobs, logprobs_n=logprobs_n,
                logit_bias=logit_bias, trace_id=trace_id,
                enqueued_at=time.time(),
            ))
        except Exception:
            # push failed — nothing is coming; don't leak the pending entry
            # (it would also keep authorizing frames for a dead request id)
            self._pending.pop(request_id, None)
            self._ctx.pop(request_id, None)
            self._failures.inc(reason="submit")
            raise
        self.remote_submitted += 1
        self._submit_t[request_id] = time.monotonic()
        self._submit_wall[request_id] = time.time()
        self._queue_depth += 1  # optimistic until the next refresh
        return fut

    def cancel(self, request_id: str, reason: str = "cancelled") -> None:
        """Stop accepting frames for a request (cancel / timeout fallback)."""
        fut = self._pending.pop(request_id, None)
        self._ctx.pop(request_id, None)
        self._submit_wall.pop(request_id, None)
        if self._submit_t.pop(request_id, None) is not None:
            self._failures.inc(reason=reason)
            flight_recorder().record(
                "disagg.cancel", request_id=request_id, reason=reason,
            )
        if fut is not None and not fut.done():
            fut.cancel()

    # ---------- transfer-server callbacks ----------

    def _authorize(self, request_id: str, block_ids) -> bool:
        return request_id in self._pending

    async def _scatter(self, request_id: str, block_ids,
                       k: np.ndarray, v: np.ndarray) -> None:
        # Stage the host→device copy in a worker thread (thread-safe, touches
        # no shared state); the cache-mutating scatter dispatch stays on the
        # event loop so it serializes with the scheduler's step calls.
        import jax

        loop = asyncio.get_running_loop()
        k_dev, v_dev = await loop.run_in_executor(
            None, lambda: (jax.device_put(k), jax.device_put(v))
        )
        # the request may have been cancelled/timed out DURING the await —
        # its blocks could already be freed and reallocated to another
        # sequence; writing now would corrupt that sequence's KV
        if request_id not in self._pending:
            logger.info("dropping late KV frame for %s", request_id)
            return
        self.runner.scatter_blocks(block_ids, k_dev, v_dev)

    def _commit(self, request_id: str, first_token: int,
                logprob: Optional[float],
                top: Optional[dict] = None,
                spans: Optional[dict] = None) -> None:
        fut = self._pending.pop(request_id, None)
        ctx = self._ctx.pop(request_id, None)
        submit_wall = self._submit_wall.pop(request_id, None)
        if fut is None or fut.done():
            logger.warning("commit for unknown request %s", request_id)
            return
        if ctx is not None:
            # closing-mark semantics (telemetry/tracing.py): the span from
            # the submit-side "admission" mark to here is the remote
            # compute + streamed KV transfer; install latency then lands
            # under the scheduler's "remote_prefill" mark
            ctx.add_stage("kv_transfer")
            if spans and submit_wall is not None:
                # the prefill worker's spans rode the commit frame: fold
                # them into this request's trace. The forward "leg" is a
                # QUEUE submit (the worker dequeues whenever it gets
                # there), so the offset comes from the commit return leg
                # alone — error bounded by the one-way commit transit,
                # not half the queue wait (queued_forward semantics in
                # telemetry/stitch.py)
                from ..telemetry.stitch import remote_span_set

                ctx.add_remote_spans(remote_span_set(
                    spans.get("source", "prefill_worker"),
                    spans.get("spans") or [],
                    spans.get("recv_at", 0.0),
                    spans.get("resp_sent_at", 0.0),
                    submit_wall, time.time(),
                    children=spans.get("children") or [],
                    queued_forward=True,
                ))
        self.remote_completed += 1
        t0 = self._submit_t.pop(request_id, None)
        if t0 is not None:
            self._rtt_hist.observe(time.monotonic() - t0)
        flight_recorder().record(
            "disagg.commit", request_id=request_id,
            rtt_s=round(time.monotonic() - t0, 4) if t0 is not None else None,
        )
        fut.set_result((first_token, logprob, top))

    def metrics(self) -> dict:
        return {
            "remote_prefill_submitted": self.remote_submitted,
            "remote_prefill_completed": self.remote_completed,
            "remote_prefill_pending": len(self._pending),
            "prefill_queue_depth": self._queue_depth,
        }
