"""Self-healing serving: watchdog-driven drain/respawn + live migration.

``controller.py`` owns the policy ladder (trip → drain → migrate →
respawn); ``migration.py`` owns the wire plane that moves an in-flight
request's committed KV + generation state to a healthy peer and relays
its continued stream back. See docs/self_healing.md.
"""

from .controller import RecoveryConfig, RecoveryController
from .migration import (
    MigrationRejected,
    MigrationServer,
    MigrationSink,
    MigrationState,
    migrate_request,
    migration_class,
    migration_key,
    package_request,
)

__all__ = [
    "RecoveryConfig",
    "RecoveryController",
    "MigrationRejected",
    "MigrationServer",
    "MigrationSink",
    "MigrationState",
    "migrate_request",
    "migration_class",
    "migration_key",
    "package_request",
]
