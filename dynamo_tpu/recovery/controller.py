"""RecoveryController: the trip → drain → migrate → respawn policy ladder.

PR 5 made wedges *visible* (StallWatchdog trips + flight artifacts);
this controller makes them *non-events*. It subscribes to watchdog trips
(and supervised-child deaths) and executes, in order:

1. **gate** — stop admission (``Scheduler.set_draining``), shed at the
   HTTP edge (``AdmissionController.set_draining``), and deregister from
   discovery so routers stop picking this worker (the ``draining`` flag
   in the worker's load snapshot excludes it from KV-router decisions
   immediately, before any scrape interval elapses on the control keys).
2. **soft drain** — give committed bursts a grace window to finish on
   their own (healthy-engine drains often empty here).
3. **seize** — stop the scheduler loop: gracefully (exit barriers
   reconcile and stream every dispatched burst) for an admin drain,
   hard (cancel; abandon un-reconciled device work — a wedged barrier
   would never finish) for a watchdog trip.
4. **migrate** — ship each live request to a healthy peer over the
   migration plane (``recovery/migration.py``): hot (KV rides along)
   when the device is trusted, cold (peer re-prefills) when it is not.
   Requests no peer accepts fail with a terminal error frame.
5. **respawn** — rebuild the engine through the supervised-child
   machinery (or an injected factory) with exponential backoff and a
   consecutive-failure budget; success re-registers in discovery and
   re-opens admission.

The same ladder minus the hard seize is ``POST /admin/drain`` — the
zero-downtime rolling-update path.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Awaitable, Callable, List, Optional

from ..protocols.common import EngineOutput, FinishReason
from ..telemetry.flight import FlightRecorder, flight_recorder
from ..telemetry.registry import MetricsRegistry
from ..transfer.ici import IciBackend
from ..transfer.plane import TransferMetrics, negotiate_backend
from .migration import migrate_request, migration_class, package_request

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class RecoveryConfig:
    # soft-drain grace: how long committed work may finish on its own
    drain_grace_s: float = 5.0
    # graceful-seize deadline before escalating to a hard cancel
    seize_timeout_s: float = 5.0
    # respawn ladder: base backoff (doubles per consecutive failure) and
    # the consecutive-failure budget before the controller gives up
    respawn_backoff_s: float = 1.0
    max_respawns: int = 3
    # master switch for live migration (False: drains fail requests)
    migrate: bool = True


class RecoveryController:
    """One per engine. All hooks are optional — a controller with only a
    respawner (subprocess-hosted engines) runs just the respawn ladder;
    one with only a scheduler (in-process engine, no supervision) runs
    drain + migrate."""

    def __init__(
        self,
        engine_id: str = "engine",
        scheduler=None,
        runner=None,
        watchdog=None,
        peers: Optional[Callable[[], List[dict]]] = None,
        respawner: Optional[Callable[[], Awaitable]] = None,
        deregister: Optional[Callable[[], Awaitable]] = None,
        register: Optional[Callable[[], Awaitable]] = None,
        admission=None,
        config: Optional[RecoveryConfig] = None,
        registry: Optional[MetricsRegistry] = None,
        flight: Optional[FlightRecorder] = None,
        # router-quality peer selection: (peers, token_ids) → peers
        # reordered best-first. Wired to KvFabric.rank_peers when the
        # worker runs a KV fabric, so a migration target is picked by
        # prefix overlap (the same ownership view the router scores)
        # instead of discovery order — the peer that already holds the
        # request's prefix resumes it with the least recompute.
        peer_ranker: Optional[Callable[[List[dict], List[int]],
                                       List[dict]]] = None,
        # ICI send plane toward migration peers: when a candidate peer
        # advertises a matching ICI receive rank, hot KV frames move
        # device-to-device instead of through host TCP buffers
        ici=None,
    ):
        self.engine_id = engine_id
        self.scheduler = scheduler
        self.runner = runner
        self.watchdog = watchdog
        self.peers = peers
        self.respawner = respawner
        self.deregister = deregister
        self.register = register
        self.admission = admission
        self.peer_ranker = peer_ranker
        if ici is not None and not isinstance(ici, IciBackend):
            ici = IciBackend(ici)
        self.ici: Optional[IciBackend] = ici
        self.config = config or RecoveryConfig()
        self.flight = flight if flight is not None else flight_recorder()
        self.registry = registry or MetricsRegistry()
        self._xfer = TransferMetrics(self.registry, plane="migration")
        self._actions = self.registry.counter(
            "dynamo_recovery_actions_total",
            "Recovery-ladder steps executed, labelled action="
            "drain|migrate|respawn|deregister|register and outcome",
        )
        self._migrations = self.registry.counter(
            "dynamo_recovery_migrations_total",
            "Live request migrations, labelled mode=hot|cold and "
            "outcome=committed|failed",
        )
        self._drain_hist = self.registry.histogram(
            "dynamo_recovery_drain_duration_seconds",
            "One drain ladder end to end: admission gate through "
            "migrations (respawn excluded — it has its own backoff)",
        )
        self._recover_task: Optional[asyncio.Task] = None
        self._relays: set = set()
        # drain subscribers (telemetry/incidents.py): called with the
        # drain info dict when the ladder engages, BEFORE any state is
        # torn down — evidence capture must see the pre-drain world
        self._drain_listeners: List[Callable[[dict], None]] = []
        # drains currently executing (the admin path runs OUTSIDE
        # _recover_task): a respawn's own kill must not read as a fresh
        # child-death and re-trigger the ladder
        self._drains_inflight = 0
        self.consecutive_respawn_failures = 0
        self.recoveries: List[dict] = []  # public record for tests
        # respawn-with-a-different-card (registry/pools.py cold start):
        # set by respawn_with_card for the duration of one respawn
        self._pending_card = None

    # ---------- subscriptions ----------

    def attach(self) -> "RecoveryController":
        if self.watchdog is not None:
            self.watchdog.add_trip_listener(self.on_trip)
        return self

    def add_drain_listener(self, fn: Callable[[dict], None]) -> None:
        """Subscribe to drain-ladder engagements (sync callback with
        ``{engine, reason, hard}``; called for BOTH automated recoveries
        and admin drains — filter on ``reason`` as needed)."""
        self._drain_listeners.append(fn)

    def on_trip(self, info: dict) -> None:
        """Watchdog trip listener (sync — called from the watchdog's
        loop). Engine-wedge reasons start the ladder; event_loop_lag is
        OUR loop lagging — recovering the engine would not help."""
        if info.get("reason") not in ("decode_stall", "no_throughput"):
            return
        self._start_recovery(info.get("reason", "trip"))

    def on_child_down(self, reason: str) -> None:
        """Supervised-child death listener (subprocess_host): the host
        already failed the in-flight streams; run the respawn ladder
        proactively so the next request doesn't pay the spawn."""
        if self._drains_inflight:
            return  # our own respawn's kill, not a fresh death
        self._start_recovery(f"child_down:{reason}")

    def _start_recovery(self, reason: str) -> None:
        if self._recover_task is not None and not self._recover_task.done():
            return  # a recovery is already running
        self._recover_task = asyncio.get_running_loop().create_task(
            self._recover(reason), name=f"recovery-{self.engine_id}"
        )

    async def _recover(self, reason: str) -> None:
        try:
            await self.drain(hard=True, respawn=True, reason=reason)
        except Exception:
            logger.exception("recovery ladder failed for %s", reason)
            self._actions.inc(action="drain", outcome="error")

    # ---------- the ladder ----------

    async def admin_drain(self, mode: str = "migrate",
                          respawn: bool = False) -> dict:
        """``POST /admin/drain`` entry: a *healthy* engine drains for a
        rolling update — graceful seize, hot migration."""
        return await self.drain(
            hard=False, migrate=(mode != "fail"), respawn=respawn,
            reason="admin",
        )

    async def drain(self, hard: bool = False, migrate: Optional[bool] = None,
                    respawn: bool = False, reason: str = "admin") -> dict:
        self._drains_inflight += 1
        try:
            return await self._drain(hard, migrate, respawn, reason)
        finally:
            self._drains_inflight -= 1

    async def _drain(self, hard: bool, migrate: Optional[bool],
                     respawn: bool, reason: str) -> dict:
        t0 = time.monotonic()
        migrate = self.config.migrate if migrate is None else migrate
        summary = {
            "reason": reason, "hard": hard, "finished": 0,
            "migrated": 0, "failed": 0, "respawned": False,
        }
        self.flight.record(
            "recovery.drain", engine=self.engine_id, reason=reason,
            hard=hard,
        )
        drain_info = {"engine": self.engine_id, "reason": reason,
                      "hard": hard}
        for fn in list(self._drain_listeners):
            try:
                fn(drain_info)
            except Exception:
                # evidence capture must never take recovery down with it
                # (and one broken listener must not starve the rest)
                logger.exception("recovery drain listener failed")
        sched = self.scheduler
        # 1. gate: no new work here, no new routing decisions toward here
        if sched is not None:
            sched.set_draining(True)
        if self.admission is not None:
            self.admission.set_draining(True)
        await self._hook("deregister", self.deregister)
        if sched is not None:
            # 2. soft grace: committed work may finish on its own (a
            # wedged loop won't — the deadline bounds the wait)
            if not hard and self.config.drain_grace_s > 0:
                deadline = time.monotonic() + self.config.drain_grace_s
                while (time.monotonic() < deadline
                       and any(s is not None for s in sched.slots)):
                    await asyncio.sleep(0.05)
            # 3. seize the loop; 4. migrate or fail what remains
            await sched.seize(
                hard=hard, timeout_s=self.config.seize_timeout_s
            )
            for er in sched.extract_requests():
                if er.finish is not None or er.ctx.is_stopped:
                    sched.allocator.free_blocks(er.block_ids)
                    er.block_ids = []
                    if er.finish is None:
                        er.out_queue.put_nowait(None)  # consumer gone
                    summary["finished"] += 1
                    continue
                outcome = "failed"
                if migrate:
                    try:
                        outcome = await self._migrate_or_fail(
                            er, allow_hot=not hard)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        # one request's packaging blowing up must not
                        # leave its siblings un-drained and hanging
                        logger.exception(
                            "migrating %s failed unexpectedly",
                            er.request_id)
                        self._fail(er, "migration failed unexpectedly")
                else:
                    self._fail(er, "engine drained without migration")
                summary["migrated" if outcome == "migrated" else "failed"] += 1
        self._actions.inc(action="drain", outcome="ok")
        self._drain_hist.observe(time.monotonic() - t0)
        # 5. respawn through the supervision machinery
        if respawn and self.respawner is not None:
            summary["respawned"] = await self._respawn(reason)
        summary["duration_s"] = round(time.monotonic() - t0, 3)
        self.recoveries.append(summary)
        logger.warning("recovery drain [%s] done: %s", reason, summary)
        return summary

    async def _migrate_or_fail(self, er, allow_hot: bool = True) -> str:
        sched = self.scheduler
        if migration_class(er) == "fail":
            self._fail(
                er, "request class cannot migrate (in-process guided "
                "state); resubmit to a healthy worker",
            )
            return "failed"
        state = package_request(
            er, sched.allocator, sched.config.kv_block_size,
            allow_hot=allow_hot and self.runner is not None,
        )
        mode = "hot" if state.hot else "cold"
        for peer in self._candidate_peers(er):
            # per-peer backend negotiation from discovery metadata: a
            # peer on the same ICI mesh (matching receive rank) takes hot
            # KV device-to-device; anyone else gets the TCP fallback
            backend = negotiate_backend(peer, self.ici,
                                        peer_role="receiver")
            gather_device = getattr(self.runner, "gather_blocks_device",
                                    None)
            use_ici = (backend == "ici" and state.hot
                       and gather_device is not None)
            try:
                relay = await migrate_request(
                    peer["host"], peer["port"], er, state,
                    gather=self.runner.gather_blocks if state.hot else None,
                    ici=self.ici if use_ici else None,
                    gather_device=gather_device if use_ici else None,
                    metrics=self._xfer,
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # nack, unreachable peer, or an unexpected failure (a
                # sick device's gather throwing) — any of them means
                # "this peer attempt is dead"; the request must still
                # end up migrated elsewhere or failed LOUDLY, never
                # abort the whole drain with siblings left hanging
                logger.warning(
                    "migration of %s to %s:%s failed: %s — trying next "
                    "peer", er.request_id, peer.get("host"),
                    peer.get("port"), e,
                )
                continue
            self._hold(relay)
            # the peer owns the KV now — release the source copy
            sched.allocator.free_blocks(er.block_ids)
            er.block_ids = []
            self._migrations.inc(mode=mode, outcome="committed")
            self._actions.inc(action="migrate", outcome="ok")
            return "migrated"
        self._migrations.inc(mode=mode, outcome="failed")
        self._fail(er, "no healthy peer accepted the migration")
        return "failed"

    def _candidate_peers(self, er=None) -> List[dict]:
        if self.peers is None:
            return []
        try:
            peers = self.peers() or []
        except Exception:
            logger.exception("peer discovery failed")
            return []
        peers = [
            p for p in peers if p.get("engine_id") != self.engine_id
        ]
        if self.peer_ranker is not None and er is not None and peers:
            # router-quality selection: order by prefix overlap with
            # this request so the peer that already holds its KV is
            # tried first (ties keep discovery order)
            try:
                peers = list(self.peer_ranker(peers, list(er.prompt)))
            except Exception:
                logger.exception("peer ranking failed; keeping "
                                 "discovery order")
        return peers

    def _fail(self, er, msg: str) -> None:
        logger.error("failing in-flight request %s: %s", er.request_id, msg)
        self.flight.record(
            "recovery.request_failed", request_id=er.request_id,
            trace_id=er.ctx.trace_id, reason=msg,
        )
        if self.scheduler is not None:
            self.scheduler.allocator.free_blocks(er.block_ids)
            er.block_ids = []
        er.finish = FinishReason.ERROR
        er.ctx.add_stage("completion")
        er.out_queue.put_nowait(
            EngineOutput(token_ids=[], finish_reason=FinishReason.ERROR)
        )
        er.out_queue.put_nowait(None)
        self._actions.inc(action="migrate", outcome="failed")

    async def respawn_with_card(self, card) -> bool:
        """Model-swap / scale-from-zero respawn: drain whatever this
        engine is serving (migrating its requests away) and rebuild it
        with a DIFFERENT model card — the one new recovery capability
        the multi-model pool plane needs (registry/pools.py cold start).
        The respawner must accept a ``card`` keyword (SubprocessEngine
        .respawn does; a factory that cannot swap cards fails loudly).

        Scope: the SINGLE-ENGINE serving shapes (in=http with a local
        supervised engine), where the frontend's own ModelManager is
        the routing truth. A dyn:// worker registered in discovery with
        ``metadata={"model": ...}`` must NOT be card-swapped in place —
        its endpoint metadata, model-registry record, and model gauge
        all still name the old model, so per-model clients and the KV
        router would route the old model's traffic to the new one.
        Fleet pools swap models by spawning fresh workers with the new
        card's flags (KubePoolBackend / StorePoolBackend) instead."""
        self._drains_inflight += 1
        try:
            self._pending_card = card
            summary = await self._drain(
                hard=False, migrate=True, respawn=True,
                reason=f"model_swap:{getattr(card, 'name', card)}",
            )
            return bool(summary.get("respawned"))
        finally:
            self._pending_card = None
            self._drains_inflight -= 1

    async def _respawn(self, reason: str) -> bool:
        delay = self.config.respawn_backoff_s
        while True:
            if self.consecutive_respawn_failures >= self.config.max_respawns:
                logger.error(
                    "respawn budget exhausted (%d consecutive failures); "
                    "%s stays down until operator action",
                    self.consecutive_respawn_failures, self.engine_id,
                )
                self._actions.inc(action="respawn", outcome="gave_up")
                return False
            try:
                if self._pending_card is not None:
                    result = await self.respawner(card=self._pending_card)
                else:
                    result = await self.respawner()
            except Exception as e:
                self.consecutive_respawn_failures += 1
                self._actions.inc(action="respawn", outcome="failed")
                logger.warning(
                    "respawn attempt failed (%d/%d): %s; backing off %.1fs",
                    self.consecutive_respawn_failures,
                    self.config.max_respawns, e, delay,
                )
                await asyncio.sleep(delay)
                delay *= 2
                continue
            self.consecutive_respawn_failures = 0
            self._actions.inc(action="respawn", outcome="ok")
            self.flight.record(
                "recovery.respawn", engine=self.engine_id, reason=reason,
            )
            if result is not None:
                # the factory rebuilt the serving stack — track the new
                # scheduler so a later drain operates on the live engine
                self.scheduler = result
            await self._hook("register", self.register)
            if self.admission is not None:
                self.admission.set_draining(False)
            return True

    async def _hook(self, name: str, fn) -> None:
        if fn is None:
            return
        try:
            result = fn()
            if asyncio.iscoroutine(result) or isinstance(
                    result, asyncio.Future):
                await result
            self._actions.inc(action=name, outcome="ok")
        except Exception:
            logger.exception("recovery %s hook failed", name)
            self._actions.inc(action=name, outcome="error")

    def _hold(self, task: asyncio.Task) -> None:
        """Keep relay tasks referenced until done; surface exceptions."""
        self._relays.add(task)

        def _done(t: asyncio.Task) -> None:
            self._relays.discard(t)
            if not t.cancelled() and t.exception() is not None:
                logger.warning("migration relay failed: %s", t.exception())

        task.add_done_callback(_done)

    async def close(self) -> None:
        tasks = list(self._relays)
        if self._recover_task is not None:
            tasks.append(self._recover_task)
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
