"""Live request migration: move an in-flight request to a healthy peer.

A draining engine packages each live request's *committed* state — the
token ids whose KV sits in its paged cache, the pending sampled token,
the generation counter, the per-request PRNG key, and the sampling/
penalty-relevant request body — and ships it to a peer engine, which
admits the request into its own cache and resumes decode mid-stream.
Two modes:

- **hot** (healthy device, e.g. ``POST /admin/drain`` rolling updates):
  the committed KV blocks ride along (gathered chunk-by-chunk, the same
  bounded-frame discipline as the streamed prefill transfer), so the
  peer resumes without recomputing anything.
- **cold** (wedged device — a hung gather would just wedge the drain
  too): only tokens ship; the peer re-prefills ``prompt + resume``
  through the scheduler's existing preemption-resume machinery, which
  already guarantees the continued stream is byte-identical.

The client's stream never breaks: the source worker keeps the client
connection and *relays* — after the peer commits, generated outputs
stream back over the same migration connection and the source forwards
them into the original request's output queue. The hop is recorded as a
``migration`` trace stage (``/debug/requests/{id}``) and a
``recovery.migrate`` flight event. Commit/poison semantics mirror
``disagg/transfer.py``: a connection that dies before commit aborts the
reservation on the receiver (blocks freed, nothing installed); a death
after commit cancels the resumed request (its relay target is gone).

**Stream re-bind** — relaying forever would pin the source worker up
just to forward a peer's bytes, defeating the drain. So the commit ack
carries a ``resume_id``, the source emits a ``migrated`` control frame
(an :class:`EngineOutput` with no payload) into the client stream, and
a re-bind-aware consumer (llm/backend.py via
:func:`follow_migrated_stream`) attaches DIRECTLY to the peer
(``mig_attach``). The peer's pump switches to the new connection —
sending ``mig_handoff`` on the old one in order, so no frame is lost
or duplicated — the source's relay ends, and the source worker can
exit. A consumer that never attaches (raw token-level readers) gets
the full relayed stream exactly as before.

Wire format (4-byte length-prefixed msgpack headers + raw payloads, the
transfer plane's framing), one migration per connection::

    → {type:"mig_begin", state:{...}, nblocks, sent_at}
    ← {type:"mig_ack", ok, reason?, recv_at, sent_at}
    → {type:"mig_blocks", offset, shape, dtype, k_bytes, v_bytes} <k> <v>
    → {type:"mig_commit"}
    ← {type:"mig_ack", ok, reason?, resume_id}
    ← {type:"mig_data", payload: EngineOutput wire} ...
    ← {type:"mig_handoff"}                     (re-bind: relay duty ends)
    ← {type:"mig_end", spans?, children?} | {type:"mig_error", error}

and, on a re-bind connection::

    → {type:"mig_attach", resume_id, sent_at}
    ← {type:"mig_ack", ok, reason?, recv_at, sent_at}
    ← {type:"mig_data", ...} ... {type:"mig_end", ...}

The ``sent_at``/``recv_at`` wall-clock pair on the begin/ack exchange is
the hop's clock-offset estimate (telemetry/stitch.py); ``mig_end`` then
piggybacks the peer's span export so the migrated request's stitched
timeline shows the resume on the peer instead of a silent gap — the
source stamps ``migration.relay`` at commit, the peer
``migration.resume`` at admit.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
import uuid
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..protocols.common import EngineOutput, FinishReason, PreprocessedRequest
from ..runtime.engine import AsyncEngineContext
from ..telemetry.flight import flight_recorder
from ..transfer.framing import pack_frame, read_header
from ..transfer.ici import IciBackend
from ..transfer.plane import maybe_drop_connection, record_open
from ..transfer.tcp import TcpBackend

logger = logging.getLogger(__name__)

# blocks per KV frame: bounds sender/receiver host buffers like the
# streamed prefill transfer's chunk frames
MIGRATE_CHUNK_BLOCKS = 16


def migration_key(namespace: str, component: str, engine_id: str) -> str:
    """Discovery-plane key a worker's migration receiver registers under
    (lease-scoped, like the KV transfer descriptor)."""
    return f"{namespace}/components/{component}/migration/{engine_id}"


class MigrationRejected(Exception):
    """The peer cannot take this request (no slot, no memory, geometry
    mismatch). The caller tries the next peer or fails the request."""


@dataclasses.dataclass
class MigrationState:
    """Everything a peer needs to resume the request byte-identically."""

    request_id: str
    trace_id: str
    req: dict                       # PreprocessedRequest.to_wire()
    # hot: tokens whose KV ships (prompt + generated, == context_len);
    # empty for a cold migration
    committed_tokens: List[int]
    # cold: generated tokens already emitted to the client (incl. the
    # pending one) — the peer re-prefills prompt + resume and continues
    resume_tokens: List[int]
    pending_token: int              # sampled, emitted, KV not yet written
    generated: int                  # max_tokens accounting + PRNG fold-in
    base_key: List[int]             # per-request PRNG key (uint32 ×2)
    prompt_lps_emitted: bool
    kv_block_size: int              # geometry must match across engines

    @property
    def hot(self) -> bool:
        return bool(self.committed_tokens)

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, d: dict) -> "MigrationState":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def migration_class(er) -> str:
    """Migrate-vs-fail decision per request class: ``hot`` | ``cold`` |
    ``fail``.

    - guided_json → **fail**: the compiled-grammar cursor lives in the
      serving layer's in-process cache; it cannot serialize, and a cold
      resume on the peer would decode unconstrained.
    - guided_choice → **cold**: ``_start_prefill`` rebuilds the trie
      constraint from the request body and walks it past the resume
      tokens — the peer reconstructs the exact cursor.
    - prompt-logprobs not yet emitted → **cold**: the accumulated
      device rows cannot ship; the peer's re-prefill recomputes them.
    - mid-prefill / still-waiting → **cold** (no complete KV to ship).
    - plain decode-state requests → **hot**.
    """
    so = er.req.sampling_options
    if so.guided_json:
        return "fail"
    if er.guided is not None or so.guided_choice_token_ids:
        return "cold"
    if er.want_prompt_lps and not er.prompt_lps_emitted:
        return "cold"
    if (er.seq is None or er.pending_token < 0
            or er.context_len != len(er.seq.token_ids)):
        return "cold"
    return "hot"


def package_request(er, allocator, kv_block_size: int,
                    allow_hot: bool = True) -> MigrationState:
    """Build the wire state from an extracted request, releasing the
    over-reserved block tail (hot) or all blocks (cold) back to the
    source allocator. After this the request holds exactly the blocks
    that must ship (hot) or none (cold)."""
    cls = migration_class(er)
    hot = allow_hot and cls == "hot"
    if hot:
        bs = kv_block_size
        keep = -(-er.context_len // bs)
        er.block_ids = allocator.rollback_tail(er.block_ids, keep)
        committed = [int(t) for t in er.seq.token_ids]
        resume: List[int] = []
    else:
        # cold: same resume computation as Scheduler._preempt — tokens
        # already emitted continue, never restart
        if er.seq is not None:
            gen = [int(t) for t in er.seq.token_ids[len(er.prompt):]]
            if er.pending_token >= 0:
                gen = gen + [int(er.pending_token)]
        else:
            gen = [int(t) for t in er.resume_tokens]
        committed = []
        resume = gen
        allocator.free_blocks(er.block_ids)
        er.block_ids = []
    return MigrationState(
        request_id=er.request_id,
        trace_id=er.ctx.trace_id,
        req=er.req.to_wire(),
        committed_tokens=committed,
        resume_tokens=resume,
        pending_token=int(er.pending_token) if hot else -1,
        generated=int(er.generated),
        base_key=[int(x) for x in np.asarray(er.base_key).tolist()]
        if er.base_key is not None else [],
        prompt_lps_emitted=bool(er.prompt_lps_emitted),
        kv_block_size=kv_block_size,
    )


# ---------------------------------------------------------------------------
# receiver
# ---------------------------------------------------------------------------
# Framing lives in the unified transfer plane (dynamo_tpu/transfer/,
# docs/transfer_plane.md): 4-byte length-prefixed msgpack headers + raw
# payloads, identical across the disagg, fabric, and migration planes.


class MigrationSink:
    """Target-side binding to one engine: reserve blocks, scatter shipped
    KV, and install the resumed request into the scheduler."""

    def __init__(self, scheduler, runner):
        self.scheduler = scheduler
        self.runner = runner
        # attempt key → (state, block_ids) reserved but not yet
        # committed. Keys are per-ATTEMPT, not per-request: a sender
        # failing over to the same receiver (ici attempt dies, tcp retry
        # follows) has two live connections for one request id, and the
        # stale attempt's connection-death abort must free ITS
        # reservation, never the retry's.
        self._pending: Dict[str, Tuple[MigrationState, List[int]]] = {}

    def reserve(self, state: MigrationState, nblocks: int) -> str:
        sched = self.scheduler
        cfg = sched.config
        if sched.draining:
            raise MigrationRejected("peer is itself draining")
        # geometry/capacity gate BEFORE any state mutates: a sequence
        # this engine cannot hold must nack here, not blow up inside
        # admit/prefill and corrupt a healthy scheduler (+1: the pending
        # token still needs a writable position below the horizon)
        prompt_len = len((state.req or {}).get("token_ids") or [])
        total = (len(state.committed_tokens)
                 or prompt_len + len(state.resume_tokens))
        if total + 1 >= cfg.max_model_len:
            raise MigrationRejected(
                f"sequence of {total} tokens exceeds this engine's "
                f"max_model_len {cfg.max_model_len}"
            )
        if nblocks > 0:
            if state.kv_block_size != cfg.kv_block_size:
                raise MigrationRejected(
                    f"kv_block_size mismatch: sender "
                    f"{state.kv_block_size} vs {cfg.kv_block_size}"
                )
            if nblocks > cfg.blocks_per_seq:
                raise MigrationRejected(
                    f"{nblocks} blocks exceed this engine's block-table "
                    f"width {cfg.blocks_per_seq}"
                )
            if sched._free_slot() is None:
                raise MigrationRejected("no free slot")
            try:
                block_ids = sched.allocator.allocate_n(nblocks)
            except MemoryError as e:
                raise MigrationRejected(f"no KV memory: {e}") from None
        else:
            block_ids = []
        mig_id = f"{state.request_id}#{uuid.uuid4().hex[:8]}"
        self._pending[mig_id] = (state, block_ids)
        return mig_id

    async def scatter(self, mig_id: str, offset: int,
                      k, v) -> None:
        entry = self._pending.get(mig_id)
        if entry is None:
            raise MigrationRejected(f"unknown migration {mig_id}")
        _state, block_ids = entry
        n = k.shape[1]
        if offset < 0 or offset + n > len(block_ids):
            raise MigrationRejected(
                f"block frame [{offset}:{offset + n}) outside reservation "
                f"of {len(block_ids)}"
            )
        if isinstance(k, np.ndarray):
            import jax

            loop = asyncio.get_running_loop()
            # stage the host→device copy off-loop (coordinator._scatter's
            # discipline); the cache-mutating scatter stays on the loop so
            # it serializes with the scheduler's own dispatches
            k_dev, v_dev = await loop.run_in_executor(
                None, lambda: (jax.device_put(k), jax.device_put(v))
            )
        else:
            # ICI path: the frame arrived as device arrays — the host
            # never touched the payload, only the header
            k_dev, v_dev = k, v
        # the migration may have been aborted during the await
        if mig_id not in self._pending:
            logger.info("dropping late migration KV frame for %s", mig_id)
            return
        self.runner.scatter_blocks(
            block_ids[offset:offset + n], k_dev, v_dev
        )

    def commit(self, mig_id: str):
        """Install the migrated request; returns the live EngineRequest
        whose out_queue the caller pumps back to the sender."""
        entry = self._pending.pop(mig_id, None)
        if entry is None:
            raise MigrationRejected(f"unknown migration {mig_id}")
        state, block_ids = entry
        # engine-side ids stay server-generated (PR 1 invariant): a
        # duplicate/replayed migration id must not collide in scheduler
        # state; the trace id alone carries cross-worker correlation
        from ..engine.scheduler import EngineRequest

        req = PreprocessedRequest.from_wire(state.req)
        er = EngineRequest(
            request_id=uuid.uuid4().hex,
            prompt=list(req.token_ids),
            req=req,
            ctx=AsyncEngineContext(trace_id=state.trace_id or None),
            out_queue=asyncio.Queue(),
        )
        er.generated = int(state.generated)
        er.pending_token = int(state.pending_token)
        er.prompt_lps_emitted = bool(state.prompt_lps_emitted)
        er.resume_tokens = [int(t) for t in state.resume_tokens]
        if state.base_key:
            er.base_key = np.asarray(state.base_key, np.uint32)
        try:
            ok = self.scheduler.admit_migrated(
                er, [int(t) for t in state.committed_tokens], block_ids
            )
        except Exception as e:
            # install failures must stay MigrationRejected (blocks freed,
            # sender nacked) — never corrupt the healthy peer's scheduler
            self.scheduler.allocator.free_blocks(block_ids)
            raise MigrationRejected(f"install failed: {e}") from e
        if not ok:
            self.scheduler.allocator.free_blocks(block_ids)
            raise MigrationRejected("no free slot at commit")
        return er

    def abort(self, mig_id: str, backend: str = "tcp",
              reason: str = "") -> None:
        entry = self._pending.pop(mig_id, None)
        if entry is not None:
            _state, block_ids = entry
            self.scheduler.allocator.free_blocks(block_ids)
            flight_recorder().record(
                "transfer.poison", plane="migration", backend=backend,
                request_id=_state.request_id, trace_id=_state.trace_id,
                reason=reason or "connection died before commit",
            )


_STREAM_END = object()  # sentinel: the out_queue terminal None, popped


class _Resume:
    """One installed migrated request and its pump-handoff state."""

    __slots__ = ("er", "attach_writer", "attach_evt", "released",
                 "pending_get", "pending_out", "done")

    def __init__(self, er):
        self.er = er
        self.attach_writer = None      # set by a mig_attach connection
        self.attach_evt = asyncio.Event()
        self.released = asyncio.Event()  # original pump gave the stream up
        # an out_queue.get in flight across the handoff: the popped-but-
        # unwritten output must reach the NEW connection, not vanish
        self.pending_get: Optional[asyncio.Task] = None
        # a popped output whose WRITE never completed (handoff, or the
        # relay connection dying mid-frame): the successor pump re-sends
        # it — exactly-once framing, byte-identity preserved
        self.pending_out = None        # EngineOutput | _STREAM_END | None
        self.done = False              # stream ended (mig_end DELIVERED)


class MigrationServer:
    """TCP receiver for inbound migrations, one migration per connection.

    After commit the connection flips to streaming mode: the resumed
    request's outputs ride back as ``mig_data`` frames until the stream
    ends. A connection death before commit aborts the reservation (the
    transfer plane's poison discipline); after commit it cancels the
    resumed request — its relay target is gone. A ``mig_attach``
    connection re-binds the stream to a direct consumer: the pump
    switches writers atomically (``mig_handoff`` closes the old
    connection's duty in order) so the source worker can exit."""

    def __init__(self, sink: MigrationSink, host: str = "127.0.0.1",
                 port: int = 0, ici=None, ici_rank: Optional[int] = None):
        self.sink = sink
        self.host = host
        self.port = port
        # device-to-device receive plane: a sender on the same ICI mesh
        # streams KV frames as collectives; the TCP connection carries
        # only headers (``mig_ici_blocks``)
        if ici is not None and not isinstance(ici, IciBackend):
            ici = IciBackend(ici)
        self.ici: Optional[IciBackend] = ici
        self.ici_rank = ici_rank
        self._server: Optional[asyncio.AbstractServer] = None
        self._resumes: Dict[str, _Resume] = {}

    async def start(self) -> "MigrationServer":
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def descriptor(self) -> dict:
        d = {"host": self.host, "port": self.port,
             "modes": ["tcp"] + (["ici"] if self.ici is not None else [])}
        if self.ici_rank is not None:
            d["ici_rank"] = self.ici_rank
        return d

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        mig_id: Optional[str] = None
        er = None
        backend = "tcp"
        try:
            while True:
                header = await read_header(reader, "migration")
                if header is None:
                    return
                mtype = header.get("type")
                if mtype == "mig_begin":
                    recv_at = time.time()
                    backend = header.get("backend") or "tcp"
                    state = MigrationState.from_wire(header["state"])
                    try:
                        mig_id = self.sink.reserve(
                            state, int(header.get("nblocks", 0))
                        )
                    except MigrationRejected as e:
                        pack_frame(writer, {"type": "mig_ack", "ok": False,
                                       "reason": str(e)})
                        await writer.drain()
                        return
                    # begin/ack is the offset-estimation pair: the sender
                    # holds its own send/receive walls, we supply ours
                    pack_frame(writer, {"type": "mig_ack", "ok": True,
                                   "recv_at": recv_at,
                                   "sent_at": time.time()})
                    await writer.drain()
                elif mtype == "mig_blocks":
                    k, v = await TcpBackend.recv_blocks(reader, header)
                    await self.sink.scatter(
                        mig_id, int(header["offset"]), k, v
                    )
                elif mtype == "mig_ici_blocks":
                    if self.ici is None or not self.ici.alive:
                        raise MigrationRejected(
                            "peer sent an ICI frame but this receiver "
                            "has no live ICI plane"
                        )
                    n = int(header["nblocks"])
                    k_dev, v_dev, seq = await self.ici.recv(n)
                    if seq != header.get("seq"):
                        # the payload's embedded seq disagrees with the
                        # header: a stale/foreign collective — scattering
                        # it would corrupt the reservation. Abort (the
                        # poison discipline), never mis-scatter.
                        raise MigrationRejected(
                            f"ICI seq mismatch: header "
                            f"{header.get('seq')} vs payload {seq}"
                        )
                    await self.sink.scatter(
                        mig_id, int(header["offset"]), k_dev, v_dev
                    )
                elif mtype == "mig_commit":
                    try:
                        er = self.sink.commit(mig_id)
                    except MigrationRejected as e:
                        pack_frame(writer, {"type": "mig_ack", "ok": False,
                                       "reason": str(e)})
                        await writer.drain()
                        return
                    mig_id = None  # installed: no reservation to abort
                    resume_id = uuid.uuid4().hex
                    resume = _Resume(er)
                    self._resumes[resume_id] = resume
                    pack_frame(writer, {"type": "mig_ack", "ok": True,
                                   "resume_id": resume_id})
                    await writer.drain()
                    handed_off = False
                    try:
                        handed_off = await self._pump(
                            resume, writer, accept_attach=True)
                    finally:
                        if (not handed_off
                                and resume.attach_writer is not None
                                and not resume.done):
                            # the relay connection died RACING an attach
                            # (the draining source exiting is exactly
                            # when consumers attach): the attached
                            # consumer owns the live stream — its pump
                            # proceeds off resume.released
                            handed_off = True
                        if not handed_off:
                            self._resumes.pop(resume_id, None)
                        if handed_off:
                            # this connection's death must NOT cancel
                            # the request: a direct consumer has it
                            er = None
                    return
                elif mtype == "mig_attach":
                    await self._handle_attach(header, writer)
                    return
                else:
                    logger.error("unknown migration frame %r", mtype)
                    return
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, OSError):
            pass
        except MigrationRejected as e:
            logger.warning("migration aborted: %s", e)
        except Exception:
            logger.exception("migration connection failed")
        finally:
            if mig_id is not None:
                # died before commit: nothing installed — free the
                # reservation (poison: a partial KV stream must never
                # become a live request)
                self.sink.abort(mig_id, backend=backend)
            if er is not None and er.finish is None:
                # died after commit: the relay (and so the client) is
                # gone — stop the resumed request
                er.ctx.stop_generating()
            writer.close()

    async def _handle_attach(self, header: dict,
                             writer: asyncio.StreamWriter) -> None:
        """A consumer re-binding a migrated stream to itself."""
        resume_id = header.get("resume_id") or ""
        resume = self._resumes.get(resume_id)
        if resume is None or resume.attach_writer is not None:
            pack_frame(writer, {"type": "mig_ack", "ok": False,
                           "reason": f"unknown or already-attached "
                                     f"resume id {resume_id!r}"})
            await writer.drain()
            return
        recv_at = time.time()
        pack_frame(writer, {"type": "mig_ack", "ok": True,
                       "recv_at": recv_at, "sent_at": time.time()})
        await writer.drain()
        resume.attach_writer = writer
        resume.attach_evt.set()
        # wait for the original pump to hand the stream off (it sends
        # mig_handoff on its own connection first, preserving order)
        await resume.released.wait()
        er = resume.er
        try:
            if not resume.done:
                await self._pump(resume, writer, accept_attach=False)
        finally:
            self._resumes.pop(resume_id, None)
            if er.finish is None and not resume.done:
                # the attached consumer died mid-stream: stop the
                # resumed request — nobody is listening anymore
                er.ctx.stop_generating()

    async def _pump(self, resume: _Resume, writer: asyncio.StreamWriter,
                    accept_attach: bool) -> bool:
        """Stream the resumed request's outputs to ``writer``; returns
        True when the stream was handed off to an attach connection."""
        er = resume.er
        try:
            while True:
                if (accept_attach and resume.attach_writer is not None
                        and writer is not resume.attach_writer):
                    # a direct consumer attached: frames written so far
                    # precede the handoff marker on this connection, all
                    # later ones go to the new connection — exactly-once
                    pack_frame(writer, {"type": "mig_handoff"})
                    await writer.drain()
                    return True
                out = resume.pending_out
                if out is None:
                    get_task = resume.pending_get
                    if get_task is None:
                        get_task = asyncio.ensure_future(
                            er.out_queue.get())
                        resume.pending_get = get_task
                    if accept_attach:
                        attach_task = asyncio.ensure_future(
                            resume.attach_evt.wait())
                        try:
                            await asyncio.wait(
                                {get_task, attach_task},
                                return_when=asyncio.FIRST_COMPLETED,
                            )
                        finally:
                            attach_task.cancel()
                        if not get_task.done():
                            continue  # woken by the attach — see above
                    fetched = await get_task
                    resume.pending_get = None
                    out = _STREAM_END if fetched is None else fetched
                    resume.pending_out = out
                if out is _STREAM_END:
                    # span export rides the stream-end frame: the peer's
                    # migration.resume → decode → completion marks (and
                    # any remote sets the peer itself collected) land in
                    # the consumer's stitched trace, not a silent gap
                    pack_frame(writer, {
                        "type": "mig_end",
                        "spans": er.ctx.export_spans(),
                        "children": list(er.ctx.remote_spans),
                    })
                    await writer.drain()
                    # done only once DELIVERED: a relay death mid-end
                    # leaves it pending for the attach pump to re-send
                    resume.done = True
                    resume.pending_out = None
                    return False
                pack_frame(writer, {"type": "mig_data",
                               "payload": out.to_wire()})
                await writer.drain()
                resume.pending_out = None
        finally:
            # whatever ended this pump (handoff, stream end, conn death),
            # a waiting attach handler must not hang on released
            resume.released.set()

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


# ---------------------------------------------------------------------------
# sender
# ---------------------------------------------------------------------------


async def migrate_request(
    host: str,
    port: int,
    er,
    state: MigrationState,
    gather=None,                  # (block_ids) -> (k, v) host arrays; hot only
    chunk_blocks: int = MIGRATE_CHUNK_BLOCKS,
    connect_timeout_s: float = 5.0,
    ici=None,                     # IciBackend toward this peer (hot only)
    gather_device=None,           # (block_ids) -> (k_dev, v_dev); ICI path
    metrics=None,                 # TransferMetrics(plane="migration")
) -> asyncio.Task:
    """Ship one request to a peer and return the spawned relay task.

    Raises ``MigrationRejected`` (peer nacked) or ``OSError``/
    ``ConnectionError`` (peer unreachable, stream died) — in both cases
    nothing was installed remotely and the caller may try another peer.
    On success the request's blocks are the caller's to free; the
    returned task relays the peer's outputs into ``er.out_queue`` until
    the stream ends (the caller holds it and cancels on shutdown).

    With ``ici`` + ``gather_device``, hot KV frames ride the ICI plane:
    the TCP connection carries only ``mig_ici_blocks`` headers while the
    payload moves device-to-device as one collective per frame — no
    whole-sequence host buffer ever materializes on either side.
    """
    block_ids = list(er.block_ids) if state.hot else []
    use_ici = (ici is not None and getattr(ici, "alive", True)
               and gather_device is not None and state.hot)
    backend = "ici" if use_ici else "tcp"
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), connect_timeout_s
    )
    loop = asyncio.get_running_loop()
    offset = rtt = 0.0
    t0 = time.monotonic()
    record_open("migration", backend, peer=f"{host}:{port}",
                trace_id=state.trace_id)
    if metrics is not None:
        metrics.channel_opened(backend)
    try:
        begin_sent = time.time()
        pack_frame(writer, {
            "type": "mig_begin", "state": state.to_wire(),
            "nblocks": len(block_ids), "sent_at": begin_sent,
            "backend": backend,
        })
        await writer.drain()
        ack = await read_header(reader, "migration")
        if ack is None or not ack.get("ok"):
            raise MigrationRejected(
                (ack or {}).get("reason", "peer closed during begin")
            )
        if ack.get("recv_at"):
            # per-hop clock offset from the begin/ack pair — applied to
            # the peer's span export when mig_end delivers it
            from ..telemetry.stitch import estimate_offset

            offset, rtt = estimate_offset(
                begin_sent, ack["recv_at"],
                ack.get("sent_at", ack["recv_at"]), time.time(),
            )
        for i in range(0, len(block_ids), chunk_blocks):
            if maybe_drop_connection("migration"):
                writer.close()
                raise ConnectionResetError(
                    "fault injected: transfer_conn_drop"
                )
            ids = block_ids[i:i + chunk_blocks]
            if use_ici:
                # device gather stays on the loop (async dispatch, no
                # host sync); only the header crosses TCP — the payload
                # rides the collective, one in flight at a time
                k_dev, v_dev = gather_device(ids)
                seq = ici.next_seq()
                pack_frame(writer, {
                    "type": "mig_ici_blocks", "offset": i,
                    "nblocks": len(ids), "seq": seq,
                })
                await writer.drain()
                nbytes = await ici.send(k_dev, v_dev, seq, len(ids))
            else:
                # the gather host-syncs device memory — off the loop,
                # chunked so host buffers stay bounded at one frame
                k, v = await loop.run_in_executor(
                    None, lambda ids=ids: gather(ids)
                )
                nbytes = await TcpBackend.send_blocks(
                    writer, {"type": "mig_blocks", "offset": i}, k, v
                )
            if metrics is not None:
                metrics.add_bytes(nbytes, backend)
        pack_frame(writer, {"type": "mig_commit"})
        await writer.drain()
        ack = await read_header(reader, "migration")
        if ack is None or not ack.get("ok"):
            raise MigrationRejected(
                (ack or {}).get("reason", "peer closed during commit")
            )
        if metrics is not None:
            metrics.observe_duration(time.monotonic() - t0, backend)
    except BaseException:
        if metrics is not None:
            metrics.channel_closed(backend)
        writer.close()
        raise
    # committed: the peer owns the request now. Stamp the hop where
    # /debug/requests/{id} will show it, then relay — the peer's half of
    # the timeline (migration.resume onward) arrives with mig_end.
    resume_id = ack.get("resume_id")
    if resume_id:
        # the re-bind offer: a follow_migrated_stream consumer attaches
        # directly to the peer and this worker's relay duty ends at the
        # handoff; consumers that ignore it get the full relay as before
        er.out_queue.put_nowait(EngineOutput(migrated={
            "host": host, "port": port, "resume_id": resume_id,
        }))
    er.ctx.add_stage("migration.relay")
    flight_recorder().record(
        "recovery.migrate", request_id=er.request_id,
        trace_id=er.ctx.trace_id, peer=f"{host}:{port}",
        hot=state.hot, blocks=len(block_ids),
        generated=int(state.generated),
    )
    return asyncio.get_running_loop().create_task(
        _relay(reader, writer, er, offset, rtt,
               metrics=metrics, backend=backend),
        name=f"mig-relay-{er.request_id[:8]}"
    )


async def _relay(reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, er,
                 offset: float = 0.0, rtt: float = 0.0,
                 metrics=None, backend: str = "tcp") -> None:
    """Forward the peer's resumed outputs into the original out_queue —
    the client's stream continues without a break. A client disconnect
    propagates to the peer by closing the connection."""
    ended = False

    async def watch_cancel():
        await er.ctx.wait_stopped()
        writer.close()  # peer sees the death and stops the request

    cancel_task = asyncio.get_running_loop().create_task(watch_cancel())
    try:
        while True:
            header = await read_header(reader, "migration")
            if header is None:
                break  # peer died mid-stream
            mtype = header.get("type")
            if mtype == "mig_data":
                er.out_queue.put_nowait(
                    EngineOutput.from_wire(header.get("payload") or {})
                )
            elif mtype == "mig_end":
                if header.get("spans"):
                    er.ctx.add_remote_spans({
                        "source": "migration_peer",
                        "spans": header["spans"],
                        "offset_s": round(offset, 6),
                        "rtt_s": round(rtt, 6),
                        "children": header.get("children") or [],
                    })
                er.out_queue.put_nowait(None)
                ended = True
                return
            elif mtype == "mig_handoff":
                # a downstream consumer attached directly to the peer:
                # relay duty ends, the source stream closes cleanly (no
                # finish — the consumer continues on its own conn), and
                # this worker is free to exit
                flight_recorder().record(
                    "recovery.migrate_handoff", request_id=er.request_id,
                    trace_id=er.ctx.trace_id,
                )
                er.out_queue.put_nowait(None)
                ended = True
                return
            elif mtype == "mig_error":
                logger.error("migrated request %s failed remotely: %s",
                             er.request_id, header.get("error"))
                break
            else:
                logger.error("unknown relay frame %r", mtype)
                break
    finally:
        cancel_task.cancel()
        writer.close()
        if metrics is not None:
            metrics.channel_closed(backend)
        if not ended and not er.ctx.is_stopped:
            # the peer (or its connection) died mid-stream: the client
            # must see a terminal frame, not silence
            er.out_queue.put_nowait(
                EngineOutput(token_ids=[],
                             finish_reason=FinishReason.ERROR)
            )
            er.out_queue.put_nowait(None)


# ---------------------------------------------------------------------------
# stream re-bind (consumer side)
# ---------------------------------------------------------------------------


async def _fold_end_spans(reader, ctx, offset: float, rtt: float,
                          timeout_s: float = 0.25) -> None:
    """Bounded read-ahead for the ``mig_end`` behind a finish frame;
    folds the peer's span export into ``ctx``. Best-effort: a peer that
    never sends it costs ``timeout_s``, nothing else."""
    try:
        end = await asyncio.wait_for(read_header(reader, "migration"), timeout_s)
    except (asyncio.TimeoutError, asyncio.IncompleteReadError,
            ConnectionResetError, OSError):
        return
    if (end and end.get("type") == "mig_end" and end.get("spans")
            and ctx is not None):
        ctx.add_remote_spans({
            "source": "migration_peer",
            "spans": end["spans"],
            "offset_s": round(offset, 6),
            "rtt_s": round(rtt, 6),
            "children": end.get("children") or [],
        })


async def _open_attach(info: dict, connect_timeout_s: float = 5.0):
    """Dial the peer and bind to a migrated request's resumed stream.
    Returns ``(reader, writer, offset, rtt)`` after the attach ack —
    the wall pair is the hop's clock-offset estimate for the span fold."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(info["host"], info["port"]),
        connect_timeout_s,
    )
    try:
        sent_at = time.time()
        pack_frame(writer, {"type": "mig_attach",
                       "resume_id": info["resume_id"],
                       "sent_at": sent_at})
        await writer.drain()
        ack = await read_header(reader, "migration")
        if ack is None or not ack.get("ok"):
            raise MigrationRejected(
                (ack or {}).get("reason", "peer closed during attach")
            )
        offset = rtt = 0.0
        if ack.get("recv_at"):
            from ..telemetry.stitch import estimate_offset

            offset, rtt = estimate_offset(
                sent_at, ack["recv_at"],
                ack.get("sent_at", ack["recv_at"]), time.time(),
            )
        return reader, writer, offset, rtt
    except BaseException:
        writer.close()
        raise


async def follow_migrated_stream(stream, ctx=None):
    """Wrap an engine's output stream, transparently re-binding across
    migrations.

    Yields :class:`EngineOutput` objects (wire dicts are decoded). On a
    ``migrated`` control frame the attach handshake starts IMMEDIATELY
    and concurrently with the source's relay — the peer switches its
    pump on receipt, the source stream ends at the handoff, and this
    generator continues byte-identically from the direct connection.
    The source worker is then free to exit. If the attach fails the
    relay keeps carrying the stream exactly as before.

    ``ctx`` (an AsyncEngineContext) receives the peer's span export
    from ``mig_end`` so the stitched trace shows the resumed half.
    """
    from contextlib import aclosing

    rebind: Optional[dict] = None
    attach_task: Optional[asyncio.Task] = None
    try:
        async with aclosing(stream) as s:
            async for out in s:
                if isinstance(out, dict):
                    out = EngineOutput.from_wire(out)
                if out.migrated:
                    rebind = dict(out.migrated)
                    attach_task = asyncio.get_running_loop().create_task(
                        _open_attach(rebind),
                        name=f"mig-attach-{rebind.get('resume_id', '?')[:8]}",
                    )
                    continue  # control frame: never client payload
                yield out
                if out.finish_reason is not None:
                    return
        # the source stream ended without a finish: a handoff (we
        # attached) — continue on the direct connection — or a genuine
        # cancellation (nothing to attach to)
        while attach_task is not None:
            try:
                reader, writer, offset, rtt = await attach_task
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.warning(
                    "stream re-bind to %s:%s failed (%s); the stream "
                    "ends with the source's relay",
                    rebind.get("host"), rebind.get("port"), e,
                )
                return
            attach_task = None
            try:
                while True:
                    header = await read_header(reader, "migration")
                    if header is None:
                        yield EngineOutput(token_ids=[],
                                           finish_reason=FinishReason.ERROR)
                        return
                    mtype = header.get("type")
                    if mtype == "mig_data":
                        out = EngineOutput.from_wire(
                            header.get("payload") or {})
                        if out.migrated:
                            # chained migration: the peer itself drained
                            rebind = dict(out.migrated)
                            attach_task = (
                                asyncio.get_running_loop().create_task(
                                    _open_attach(rebind)))
                            continue
                        if out.finish_reason is not None:
                            # mig_end (the span export) is right behind
                            # the finish frame — read it BEFORE yielding,
                            # because a detokenizing consumer breaks (and
                            # acloses us) at the finish chunk
                            await _fold_end_spans(reader, ctx, offset, rtt)
                            yield out
                            return
                        yield out
                    elif mtype == "mig_end":
                        if header.get("spans") and ctx is not None:
                            ctx.add_remote_spans({
                                "source": "migration_peer",
                                "spans": header["spans"],
                                "offset_s": round(offset, 6),
                                "rtt_s": round(rtt, 6),
                                "children": header.get("children") or [],
                            })
                        break  # an attach_task from a chained migration continues
                    elif mtype == "mig_error":
                        yield EngineOutput(token_ids=[],
                                           finish_reason=FinishReason.ERROR)
                        return
                    else:
                        logger.error("unknown attach frame %r", mtype)
                        return
            finally:
                writer.close()
    finally:
        if attach_task is not None:
            if (attach_task.done() and not attach_task.cancelled()
                    and attach_task.exception() is None):
                # the handshake completed but the stream ended through
                # the relay first — cancel() would be a no-op on the
                # done task, leaking the opened connection
                attach_task.result()[1].close()
            else:
                attach_task.cancel()
