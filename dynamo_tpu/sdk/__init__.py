"""Python SDK: declare inference graphs, serve them locally or distributed.

Surface parity with the reference SDK (reference: deploy/dynamo/sdk —
@service / @dynamo_endpoint / depends / ServiceConfig / dynamo serve):

    from dynamo_tpu.sdk import service, dynamo_endpoint, depends

    @service(dynamo={"namespace": "public"}, resources={"tpu": 1})
    class Worker:
        @dynamo_endpoint
        async def generate(self, request):
            yield {"text": "..."}

    @service(workers=2)
    class Frontend:
        worker = depends(Worker)
        @dynamo_endpoint
        async def chat(self, request):
            async for out in self.worker.generate(request):
                yield out

    Frontend.link(Worker)   # graph edge, reference-style chaining

Serve in one process (tests / single host) with
serving.serve_graph_inprocess, or one process per worker with
serving.GraphSupervisor (TPU chips assigned per worker by
allocator.TpuAllocator).
"""

from .allocator import AllocationError, TpuAllocator
from .config import ServiceConfig
from .service import (
    Dependency,
    DynamoClient,
    ServiceDefinition,
    async_on_start,
    depends,
    dynamo_endpoint,
    graph_services,
    service,
)
from .serving import GraphSupervisor, serve_graph_inprocess, stop_graph
from .worker import serve_service

__all__ = [
    "AllocationError",
    "TpuAllocator",
    "ServiceConfig",
    "Dependency",
    "DynamoClient",
    "ServiceDefinition",
    "async_on_start",
    "depends",
    "dynamo_endpoint",
    "graph_services",
    "service",
    "GraphSupervisor",
    "serve_graph_inprocess",
    "stop_graph",
    "serve_service",
]
