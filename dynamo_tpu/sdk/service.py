"""Inference-graph composition: @service, @dynamo_endpoint, depends, link.

The reference SDK's surface (reference: deploy/dynamo/sdk/src/dynamo/sdk/
lib/{service,decorators,dependency}.py — @service(dynamo={...},
resources={...}, workers=N), @dynamo_endpoint, depends(Other) proxying
endpoint streams, and graphs like Frontend.link(Processor).link(Worker)
in examples/llm/graphs/*.py), rebuilt over this framework's runtime:
component = service name, endpoint = decorated method, transport = the
dynstore/memory planes in dynamo_tpu.runtime.
"""

from __future__ import annotations

import dataclasses
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Set

_ENDPOINT_ATTR = "__dynamo_endpoint__"
_ON_START_ATTR = "__dynamo_on_start__"


def dynamo_endpoint(name: Optional[str] = None):
    """Mark an async-generator method as a served endpoint."""

    def wrap(fn):
        setattr(fn, _ENDPOINT_ATTR, name or fn.__name__)
        return fn

    # bare usage: @dynamo_endpoint over the function itself
    if callable(name):
        fn, name = name, None
        return wrap(fn)
    return wrap


def async_on_start(fn):
    """Mark an async method to run once before endpoints start serving."""
    setattr(fn, _ON_START_ATTR, True)
    return fn


class Dependency:
    """Declared with ``depends(Other)`` as a class attribute; resolved to a
    DynamoClient when the service is instantiated by the worker runner."""

    def __init__(self, target: "ServiceDefinition"):
        if not isinstance(target, ServiceDefinition):
            raise TypeError("depends() takes a @service-decorated class")
        self.target = target

    def __repr__(self):
        return f"depends({self.target.name})"


def depends(target: "ServiceDefinition") -> Dependency:
    return Dependency(target)


@dataclasses.dataclass
class ServiceSpec:
    namespace: str = "public"
    enabled: bool = True
    resources: Dict[str, Any] = dataclasses.field(default_factory=dict)
    workers: int = 1


class ServiceDefinition:
    """A @service-decorated class: metadata + graph edges."""

    def __init__(self, cls: type, spec: ServiceSpec):
        self.cls = cls
        self.name = cls.__name__
        self.spec = spec
        self.endpoints: Dict[str, str] = {}   # endpoint name -> method name
        self.on_start: List[str] = []
        self.dependencies: Dict[str, Dependency] = {}
        self.links: List["ServiceDefinition"] = []
        # walk the whole MRO so endpoints/hooks/depends declared on base
        # classes are honored; later (more-derived) definitions win
        attrs: Dict[str, Any] = {}
        for klass in reversed(cls.__mro__):
            attrs.update(vars(klass))
        for attr, value in attrs.items():
            if callable(value) and hasattr(value, _ENDPOINT_ATTR):
                self.endpoints[getattr(value, _ENDPOINT_ATTR)] = attr
            if callable(value) and getattr(value, _ON_START_ATTR, False):
                self.on_start.append(attr)
            if isinstance(value, Dependency):
                self.dependencies[attr] = value

    def link(self, other: "ServiceDefinition") -> "ServiceDefinition":
        """Add a graph edge self → other; returns ``other`` so chains read
        Frontend.link(Processor).link(Worker) like the reference graphs."""
        if other not in self.links:
            self.links.append(other)
        return other

    def unlink_all(self) -> None:
        """Drop this service's graph edges (link state is process-global —
        one graph per process in production; tests composing several graphs
        over the same services reset between them)."""
        self.links.clear()

    def endpoint_path(self, endpoint: str) -> str:
        return f"dyn://{self.spec.namespace}.{self.name}.{endpoint}"

    def __repr__(self):
        return f"<service {self.name} endpoints={sorted(self.endpoints)}>"


def service(
    cls: Optional[type] = None,
    *,
    dynamo: Optional[dict] = None,
    resources: Optional[dict] = None,
    workers: int = 1,
):
    """Class decorator declaring a deployable service."""

    def wrap(cls: type) -> ServiceDefinition:
        dyn = dynamo or {}
        spec = ServiceSpec(
            namespace=dyn.get("namespace", "public"),
            enabled=dyn.get("enabled", True),
            resources=resources or {},
            workers=workers,
        )
        return ServiceDefinition(cls, spec)

    return wrap(cls) if cls is not None else wrap


def graph_services(root: ServiceDefinition) -> List[ServiceDefinition]:
    """Every service reachable from ``root`` via links and dependencies,
    in deterministic discovery order (root first)."""
    seen: Set[int] = set()
    out: List[ServiceDefinition] = []

    def visit(svc: ServiceDefinition) -> None:
        if id(svc) in seen:
            return
        seen.add(id(svc))
        out.append(svc)
        for dep in svc.dependencies.values():
            visit(dep.target)
        for linked in svc.links:
            visit(linked)

    visit(root)
    return out


class DynamoClient:
    """Resolved ``depends``: one attribute per target endpoint, each an
    async-generator call routing through the runtime Client."""

    def __init__(self, target: ServiceDefinition, drt, router_mode=None):
        from ..runtime.client import Client, RouterMode

        self._target = target
        self._clients: Dict[str, Any] = {}
        ns = drt.namespace(target.spec.namespace)
        comp = ns.component(target.name)
        for ep_name in target.endpoints:
            client = Client(
                comp.endpoint(ep_name), router_mode or RouterMode.ROUND_ROBIN
            )
            self._clients[ep_name] = client

    async def start(self) -> "DynamoClient":
        for client in self._clients.values():
            await client.start()
        return self

    async def wait_ready(self, timeout: float = 10.0) -> None:
        for client in self._clients.values():
            await client.wait_for_instances(timeout=timeout)

    def __getattr__(self, name: str) -> Callable[[Any], AsyncIterator[Any]]:
        try:
            client = self._clients[name]
        except KeyError:
            raise AttributeError(
                f"{self._target.name} has no endpoint {name!r}; "
                f"available: {sorted(self._clients)}"
            ) from None

        def call(payload: Any) -> AsyncIterator[Any]:
            from ..runtime.engine import Context

            return client.generate(Context(payload))

        return call
