"""Graph artifact packaging: the ``dynamo build`` analog.

Reference analog: deploy/dynamo/sdk/src/dynamo/sdk/cli/{build,bentos}.py
— a graph target is packaged into a versioned, content-addressed bundle
(``name:version``) that the api-store registers and the operator deploys
by version, so a cluster deploy pins exactly what it runs. Here the
bundle is a plain tarball (no container build — the runtime image is a
deploy-time concern on TPU pods):

    <name>-<version>.dyn.tar.gz
    ├── manifest.json      # the record below
    ├── config.yaml        # the graph's service config, verbatim
    └── src/<files...>     # source of every service class in the graph

``version`` is the first 12 hex chars of the sha256 over the manifest's
content-bearing fields (graph target, service topology, config, code
digests, model-card checksums) — the same build twice gives the same
version; any drift in code or config gives a new one.

CLI:
    python -m dynamo_tpu.sdk.build examples.llm.graphs.agg:Frontend \
        -f examples/llm/configs/agg.yaml -o ./artifacts
    python -m dynamo_tpu.sdk.build --inspect artifacts/agg-ab12cd34ef56.dyn.tar.gz

Deploy by artifact:  llmctl deploy create NAME --from-artifact <tarball>
— the store record's spec embeds {artifact: {name, version, ...}} and
the operator surfaces the version in the CR status (artifactVersion).
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import importlib
import importlib.util
import io
import json
import os
import subprocess
import sys
import tarfile
from typing import Dict, List, Optional

from .service import ServiceDefinition, graph_services

SCHEMA = "dynamo-tpu/artifact.v1"

# service-class → operator role mapping (deploy/operator.py ROLE_ARGS);
# anything unrecognized deploys as a generic worker unless the config
# names a role explicitly
_KNOWN_ROLES = {
    "frontend": "frontend",
    "processor": "processor",
    "worker": "worker",
    "decode": "decode",
    "decodeworker": "decode",
    "prefill": "prefill",
    "prefillworker": "prefill",
}


@dataclasses.dataclass
class Artifact:
    path: str
    manifest: dict

    @property
    def name(self) -> str:
        return self.manifest["name"]

    @property
    def version(self) -> str:
        return self.manifest["version"]


def _load_target(target: str) -> ServiceDefinition:
    """``pkg.module:Service`` or ``path/to/file.py:Service`` → the root
    ServiceDefinition of the graph."""
    if ":" not in target:
        raise ValueError(
            f"graph target {target!r} must be '<module-or-file>:<Service>'"
        )
    mod_ref, attr = target.rsplit(":", 1)
    if mod_ref.endswith(".py") or os.path.sep in mod_ref:
        spec = importlib.util.spec_from_file_location(
            "dynamo_graph_" + hashlib.sha256(mod_ref.encode()).hexdigest()[:8],
            mod_ref,
        )
        if spec is None:
            raise FileNotFoundError(mod_ref)
        module = importlib.util.module_from_spec(spec)
        # without the sys.modules entry, inspect.getsourcefile on classes
        # defined in the file raises TypeError — which would silently ship
        # an artifact with no code digests
        sys.modules[spec.name] = module
        spec.loader.exec_module(module)
    else:
        module = importlib.import_module(mod_ref)
    root = getattr(module, attr)
    if not isinstance(root, ServiceDefinition):
        raise TypeError(f"{target} is not a @service-decorated class")
    return root


def _service_record(svc: ServiceDefinition) -> dict:
    name = svc.cls.__name__
    return {
        "class": name,
        "role": _KNOWN_ROLES.get(name.lower(), "worker"),
        "namespace": svc.spec.namespace,
        "workers": svc.spec.workers,
        "resources": svc.spec.resources,
        "endpoints": sorted(svc.endpoints),
        "links": [d.cls.__name__ for d in svc.links],
    }


def _source_files(services: List[ServiceDefinition]) -> List[str]:
    import inspect

    files = []
    for svc in services:
        try:
            f = inspect.getsourcefile(svc.cls)
        except TypeError:
            f = None
        if f and os.path.exists(f) and f not in files:
            files.append(f)
    return files


def _git_commit(paths: List[str]) -> Optional[str]:
    anchor = os.path.dirname(os.path.abspath(paths[0])) if paths else "."
    try:
        out = subprocess.run(
            ["git", "-C", anchor, "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except (OSError, subprocess.TimeoutExpired):
        return None


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return "sha256:" + h.hexdigest()


def _model_cards(config: dict) -> Dict[str, str]:
    """Checksums of every model a config references (pin the weights a
    version deploys, not just the code)."""
    cards: Dict[str, str] = {}

    def walk(node):
        if isinstance(node, dict):
            for k, v in node.items():
                if k in ("model-path", "model_path", "modelPath") and \
                        isinstance(v, str) and os.path.isdir(v):
                    try:
                        from ..llm.model_card import ModelDeploymentCard

                        cards[v] = ModelDeploymentCard.from_local_path(v).checksum
                    # dynlint: allow(silent-except) - failure IS recorded: checksum "unavailable"
                    except Exception:  # unreadable model dir: record absence
                        cards[v] = "unavailable"
                else:
                    walk(v)
        elif isinstance(node, list):
            for v in node:
                walk(v)

    walk(config)
    return cards


def build_artifact(
    target: str,
    config_path: Optional[str] = None,
    output_dir: str = ".",
    name: Optional[str] = None,
) -> Artifact:
    root = _load_target(target)
    services = graph_services(root)
    config: dict = {}
    config_text = ""
    if config_path:
        from .config import _load_text

        with open(config_path) as f:
            config_text = f.read()
        config = _load_text(config_text) or {}

    src_files = _source_files(services)
    repo_anchor = os.path.commonpath(src_files) if src_files else "."
    if os.path.isfile(repo_anchor):
        repo_anchor = os.path.dirname(repo_anchor)
    digests = {
        os.path.relpath(f, repo_anchor): _sha256_file(f) for f in src_files
    }

    mod_ref = target.rsplit(":", 1)[0]
    default_name = (
        os.path.basename(mod_ref).removesuffix(".py")
        if mod_ref.endswith(".py") or os.path.sep in mod_ref
        else mod_ref.rsplit(".", 1)[-1]
    )
    manifest = {
        "schema": SCHEMA,
        "name": name or default_name,
        "graph_target": target,
        "services": {
            svc.cls.__name__: _service_record(svc) for svc in services
        },
        "config": config,
        "code": {
            "git_commit": _git_commit(src_files),
            "digests": digests,
        },
        "model_cards": _model_cards(config),
    }
    # content-addressed version: everything that changes what would run.
    # created/git_commit excluded — a rebuild of identical content from a
    # dirty checkout or at a later time must not mint a new version
    basis = json.dumps(
        {k: manifest[k] for k in
         ("schema", "graph_target", "services", "config", "model_cards")}
        | {"digests": digests},
        sort_keys=True,
    ).encode()
    manifest["version"] = hashlib.sha256(basis).hexdigest()[:12]

    os.makedirs(output_dir, exist_ok=True)
    out_path = os.path.join(
        output_dir, f"{manifest['name']}-{manifest['version']}.dyn.tar.gz"
    )

    def add_bytes(tar, arcname, data: bytes):
        info = tarfile.TarInfo(arcname)
        info.size = len(data)
        info.mtime = 0
        tar.addfile(info, io.BytesIO(data))

    # byte-identical archives for identical content: entry mtimes are
    # zeroed AND the gzip header's embedded timestamp is pinned (no
    # "created" field in the manifest — the api-store records creation
    # time; the artifact records only what runs)
    import gzip

    with open(out_path, "wb") as raw, \
            gzip.GzipFile(fileobj=raw, mode="wb", mtime=0) as gz, \
            tarfile.open(fileobj=gz, mode="w") as tar:
        add_bytes(tar, "manifest.json",
                  json.dumps(manifest, indent=2).encode())
        if config_path:
            add_bytes(tar, "config" + os.path.splitext(config_path)[1],
                      config_text.encode())
        for f in src_files:
            with open(f, "rb") as fh:
                add_bytes(tar, os.path.join(
                    "src", os.path.relpath(f, repo_anchor)), fh.read())
    return Artifact(path=out_path, manifest=manifest)


def inspect_artifact(path: str) -> dict:
    with tarfile.open(path, "r:gz") as tar:
        try:
            f = tar.extractfile("manifest.json")
        except KeyError:
            f = None
        if f is None:
            raise ValueError(f"{path}: no manifest.json")
        manifest = json.loads(f.read().decode())
    if manifest.get("schema") != SCHEMA:
        raise ValueError(
            f"{path}: unknown artifact schema {manifest.get('schema')!r}"
        )
    return manifest


def deployment_spec(manifest: dict) -> dict:
    """Render an api-store/CR deployment spec from an artifact manifest —
    what ``llmctl deploy create --from-artifact`` registers and
    deploy/operator.py renders into cluster manifests."""
    services: Dict[str, dict] = {}
    for cls_name, rec in manifest["services"].items():
        svc: dict = {"role": rec["role"], "replicas": rec.get("workers", 1)}
        tpus = (rec.get("resources") or {}).get("tpu")
        if tpus:
            svc["tpus"] = tpus
        services[cls_name.lower()] = svc
    # per-service config carries deploy fields through, with the sdk's
    # Common/common-configs inheritance applied (ServiceConfig.get — the
    # same merge serve-time uses, so e.g. a model-path a Worker opts into
    # from Common reaches the rendered spec)
    from .config import ServiceConfig

    cfg = ServiceConfig(manifest.get("config") or {})
    for cls_name in manifest["services"]:
        key = cls_name.lower()
        merged = cfg.get(cls_name)
        for src_key, dst_key in (
            ("model-path", "modelPath"), ("model_path", "modelPath"),
            ("modelPath", "modelPath"), ("model-name", "modelName"),
            ("replicas", "replicas"),
            ("env", "env"), ("extraArgs", "extraArgs"),
        ):
            if src_key in merged:
                services[key][dst_key] = merged[src_key]
    spec: dict = {
        "services": services,
        "artifact": {
            "name": manifest["name"],
            "version": manifest["version"],
            "graphTarget": manifest["graph_target"],
            "gitCommit": (manifest.get("code") or {}).get("git_commit"),
            "modelCards": manifest.get("model_cards") or {},
        },
    }
    ns = {rec["namespace"] for rec in manifest["services"].values()}
    if len(ns) == 1:
        spec["namespace"] = ns.pop()
    return spec


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="dynamo-build",
        description="package a service graph into a versioned artifact",
    )
    p.add_argument("target", nargs="?",
                   help="<module-or-file>:<ServiceClass> graph root")
    p.add_argument("-f", "--config", default=None, help="graph YAML config")
    p.add_argument("-o", "--output-dir", default=".")
    p.add_argument("--name", default=None, help="artifact name override")
    p.add_argument("--inspect", default=None, metavar="TARBALL",
                   help="print an artifact's manifest and exit")
    args = p.parse_args(argv)
    if args.inspect:
        print(json.dumps(inspect_artifact(args.inspect), indent=2))
        return 0
    if not args.target:
        p.error("target is required (or use --inspect)")
    art = build_artifact(
        args.target, config_path=args.config,
        output_dir=args.output_dir, name=args.name,
    )
    print(f"built {art.name}:{art.version} -> {art.path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
