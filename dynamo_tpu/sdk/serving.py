"""Graph supervisor: one process per service worker, chips pre-assigned.

The ``dynamo serve`` analog (reference: deploy/dynamo/sdk/src/dynamo/sdk/
cli/serving.py:130-505 — circus-based per-service watchers). Spawns
``python -m dynamo_tpu.sdk.worker`` per worker with TPU chips from the
allocator, monitors children, and tears the group down together.

Also provides ``serve_graph_inprocess`` — every service bound in one
process over one DistributedRuntime — which is both the test harness and
the single-host fast path (no process or serialization overhead between
services that fit one host).
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import subprocess
import sys
from typing import Dict, List, Optional

from ..runtime.component import DistributedRuntime
from .allocator import TpuAllocator
from .config import ServiceConfig
from .service import ServiceDefinition, graph_services
from .worker import serve_service

logger = logging.getLogger(__name__)


class GraphSupervisor:
    def __init__(
        self,
        graph_spec: str,           # module:Attr for worker processes
        root: ServiceDefinition,
        store_host: str = "127.0.0.1",
        store_port: int = 4871,
        config_file: Optional[str] = None,
        allocator: Optional[TpuAllocator] = None,
    ):
        self.graph_spec = graph_spec
        self.root = root
        self.store_host = store_host
        self.store_port = store_port
        self.config_file = config_file
        self.allocator = allocator or TpuAllocator()
        self.procs: List[subprocess.Popen] = []
        self._proc_chips: Dict[int, List[int]] = {}  # pid → assigned chips

    def start(self) -> None:
        try:
            for svc in graph_services(self.root):
                if not svc.spec.enabled:
                    continue
                for worker_idx in range(svc.spec.workers):
                    env = dict(os.environ)
                    extra, chips = self.allocator.env_for(svc.spec.resources)
                    env.update(extra)
                    cmd = [
                        sys.executable, "-m", "dynamo_tpu.sdk.worker",
                        self.graph_spec, "--service", svc.name,
                        "--store-host", self.store_host,
                        "--store-port", str(self.store_port),
                    ]
                    if self.config_file:
                        cmd += ["--config-file", self.config_file]
                    try:
                        proc = subprocess.Popen(cmd, env=env)
                    except Exception:
                        # chips were assigned for this worker but no process
                        # will ever own them — give them back before unwinding
                        self.allocator.release(chips)
                        raise
                    logger.info(
                        "started %s worker %d (pid %d)", svc.name, worker_idx, proc.pid
                    )
                    self.procs.append(proc)
                    self._proc_chips[proc.pid] = chips
        except Exception:
            # e.g. AllocationError mid-graph: don't leave earlier workers
            # running with chips held
            self.stop()
            raise

    def poll(self) -> Dict[int, Optional[int]]:
        """pid → returncode (None while running)."""
        return {p.pid: p.poll() for p in self.procs}

    def stop(self, timeout: float = 10.0) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in self.procs:
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
            self.allocator.release(self._proc_chips.pop(p.pid, []))
        self.procs.clear()


async def serve_graph_inprocess(
    root: ServiceDefinition,
    drt: Optional[DistributedRuntime] = None,
    config: Optional[ServiceConfig] = None,
):
    """Bind every service in ``root``'s graph in this process.

    Services are started leaves-first so depends() targets are discoverable
    before their consumers resolve clients. Returns (drt, handles, objects)
    — ``objects`` maps service name → live instance (e.g. to reach the
    Frontend's bound HTTP port); caller owns shutdown via ``stop_graph``.
    """
    drt = drt or DistributedRuntime.in_process()
    services = list(reversed(graph_services(root)))  # leaves first
    all_handles = []
    objects: Dict[str, object] = {}
    for svc in services:
        if not svc.spec.enabled:
            continue
        obj, handles = await serve_service(svc, drt, config)
        objects[svc.name] = obj
        all_handles.extend(handles)
    return drt, all_handles, objects


async def stop_graph(drt: DistributedRuntime, handles) -> None:
    for h in handles:
        await h.stop()
    await drt.close()
