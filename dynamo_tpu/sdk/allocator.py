"""TPU chip allocator for the serve supervisor.

The analog of the reference SDK's GPU allocator (reference:
deploy/dynamo/sdk/src/dynamo/sdk/cli/allocator.py:35-136 —
ResourceAllocator.assign_gpus setting CUDA_VISIBLE_DEVICES per worker):
each spawned worker gets a disjoint set of local TPU chips via
TPU_VISIBLE_CHIPS (honored by libtpu), plus JAX flags so CPU-only services
don't initialize the TPU at all.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional


class AllocationError(RuntimeError):
    pass


class TpuAllocator:
    def __init__(self, total_chips: Optional[int] = None):
        if total_chips is None:
            env = os.environ.get("DYNAMO_TPU_NUM_CHIPS")
            total_chips = int(env) if env else self._detect()
        self.total_chips = total_chips
        self._free: List[int] = list(range(total_chips))

    @staticmethod
    def _detect() -> int:
        """Best-effort local chip count without initializing JAX."""
        visible = os.environ.get("TPU_VISIBLE_CHIPS")
        if visible:
            return len([c for c in visible.split(",") if c.strip()])
        # /dev/accel* is how libtpu exposes local chips
        try:
            return len([d for d in os.listdir("/dev") if d.startswith("accel")]) or 0
        except OSError:
            return 0

    @property
    def available(self) -> int:
        return len(self._free)

    def assign(self, count: int) -> List[int]:
        """Take ``count`` chips; raises when over-subscribed."""
        if count > self.available:
            raise AllocationError(
                f"need {count} TPU chips, {self.available} of {self.total_chips} left"
            )
        chips, self._free = self._free[:count], self._free[count:]
        return chips

    def release(self, chips: List[int]) -> None:
        """Return chips (e.g. their worker exited) for reassignment."""
        self._free = sorted(set(self._free) | set(chips))

    def env_for(self, resources: Dict):
        """(env, chips) for one worker given its resource request
        ({'tpu': N} or none for CPU-only services). The caller owns the
        returned chips and should ``release`` them when the worker exits."""
        n = int(resources.get("tpu", 0))
        if n <= 0:
            # CPU-only service: keep JAX (if imported at all) off the TPU
            return {"JAX_PLATFORMS": "cpu"}, []
        chips = self.assign(n)
        return {"TPU_VISIBLE_CHIPS": ",".join(str(c) for c in chips)}, chips
