"""Service configuration: YAML/JSON sections per service + Common opt-in.

Mirrors the reference SDK's ServiceConfig behavior (reference:
deploy/dynamo/sdk/src/dynamo/sdk/lib/config.py, semantics pinned by
tests/test_config.py): the config document maps service name → options; a
``Common`` section holds shared values; a service pulls specific Common
keys by listing them under ``common-configs``. ``as_args`` renders a
service's merged options as CLI flags for its worker process.

Sources (first match wins): explicit path/dict, the
``DYNAMO_TPU_SERVICE_CONFIG`` environment variable (JSON or YAML text).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

COMMON_SECTION = "Common"
COMMON_KEY = "common-configs"
ENV_VAR = "DYNAMO_TPU_SERVICE_CONFIG"


def _load_text(text: str) -> dict:
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        import yaml

        return yaml.safe_load(text)


class ServiceConfig:
    _instance: Optional["ServiceConfig"] = None

    def __init__(self, data: Optional[dict] = None):
        self.data: dict = data or {}

    @classmethod
    def get_instance(cls) -> "ServiceConfig":
        if cls._instance is None:
            text = os.environ.get(ENV_VAR)
            cls._instance = cls(_load_text(text) if text else {})
        return cls._instance

    @classmethod
    def from_file(cls, path: str) -> "ServiceConfig":
        with open(path) as f:
            return cls(_load_text(f.read()) or {})

    def get(self, service: str) -> Dict[str, Any]:
        """Service options merged with its opted-in Common keys.

        Explicit service values win over Common values for the same key.
        Unknown opted-in keys are ignored (a service may opt into keys only
        some deployments define).
        """
        section = dict(self.data.get(service, {}))
        wanted = section.pop(COMMON_KEY, [])
        common = self.data.get(COMMON_SECTION, {})
        merged: Dict[str, Any] = {
            k: common[k] for k in wanted if k in common
        }
        merged.update(section)
        return merged

    def as_args(self, service: str) -> List[str]:
        """Render options as CLI flags: bools become bare flags (False →
        omitted), everything else ``--key value``."""
        args: List[str] = []
        for key, value in self.get(service).items():
            flag = f"--{key}"
            if isinstance(value, bool):
                if value:
                    args.append(flag)
            elif isinstance(value, (list, tuple)):
                for item in value:
                    args.extend([flag, str(item)])
            else:
                args.extend([flag, str(value)])
        return args
