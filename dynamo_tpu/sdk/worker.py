"""Per-worker service entrypoint: bind a @service class to the runtime.

The serve_dynamo analog (reference: deploy/dynamo/sdk/src/dynamo/sdk/cli/
serve_dynamo.py:38-184 — create DRT, create_service, bind endpoints, run
async_on_start hooks, serve). The supervisor (sdk/serving.py) execs this
module once per worker:

    python -m dynamo_tpu.sdk.worker graphs.agg:Frontend --service Processor \
        --store-port 4871
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import inspect
import logging
import signal
import sys
from typing import List, Optional

from ..runtime.component import DistributedRuntime
from .config import ServiceConfig
from .service import DynamoClient, ServiceDefinition, graph_services

logger = logging.getLogger(__name__)


def load_graph_root(spec: str) -> ServiceDefinition:
    """'pkg.module:Attr' → the ServiceDefinition bound to Attr."""
    module_name, _, attr = spec.partition(":")
    if not attr:
        raise ValueError(f"graph spec {spec!r} must be module:Attr")
    module = importlib.import_module(module_name)
    root = getattr(module, attr)
    if not isinstance(root, ServiceDefinition):
        raise TypeError(f"{spec} is not a @service (got {type(root)})")
    return root


def find_service(root: ServiceDefinition, name: Optional[str]) -> ServiceDefinition:
    if name is None:
        return root
    for svc in graph_services(root):
        if svc.name == name:
            return svc
    raise LookupError(f"service {name!r} not in graph of {root.name}")


async def serve_service(
    svc: ServiceDefinition,
    drt: DistributedRuntime,
    config: Optional[ServiceConfig] = None,
):
    """Instantiate the service class, resolve depends(), run hooks, serve
    every endpoint. Returns (instance, [ServingEndpoint])."""
    obj = svc.cls()
    obj.service_config = (config or ServiceConfig.get_instance()).get(svc.name)
    obj.drt = drt

    for attr, dep in svc.dependencies.items():
        client = DynamoClient(dep.target, drt)
        await client.start()
        setattr(obj, attr, client)

    for method_name in svc.on_start:
        await getattr(obj, method_name)()

    comp = drt.namespace(svc.spec.namespace).component(svc.name)
    handles = []
    # services may expose worker-style plumbing: a stats RPC payload
    # (ForwardPassMetrics for KV-aware routers) and a pinned instance id
    # matching their KV event publisher (see examples/llm/components.py)
    stats_handler = getattr(obj, "stats_handler", None)
    instance_id = getattr(obj, "instance_id", None)
    for ep_name, method_name in svc.endpoints.items():
        method = getattr(obj, method_name)

        def make_handler(m):
            # endpoints may take (request) or (request, ctx) — pass the
            # engine context through so cooperative stop reaches user code.
            # Only REQUIRED positional params count: an optional second
            # param (e.g. temperature=0.7) must not receive the Context.
            required = [
                p for p in inspect.signature(m).parameters.values()
                if p.default is p.empty
                and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            ]
            wants_ctx = len(required) >= 2

            async def handler(payload, ctx):
                agen = m(payload, ctx) if wants_ctx else m(payload)
                async for item in agen:
                    if ctx.is_stopped:
                        break
                    yield item

            return handler

        serving = await comp.endpoint(ep_name).serve(
            make_handler(method),
            instance_id=instance_id,
            stats_handler=stats_handler,
        )
        handles.append(serving)
        logger.info("serving %s", svc.endpoint_path(ep_name))
    return obj, handles


async def amain(argv: List[str]) -> None:
    p = argparse.ArgumentParser(prog="dynamo-tpu sdk worker")
    p.add_argument("graph", help="module:Attr of the graph root @service")
    p.add_argument("--service", default=None, help="service name (default: root)")
    p.add_argument("--store-host", default="127.0.0.1")
    p.add_argument("--store-port", type=int, required=True)
    p.add_argument("--config-file", default=None)
    args = p.parse_args(argv)

    root = load_graph_root(args.graph)
    svc = find_service(root, args.service)
    config = (
        ServiceConfig.from_file(args.config_file)
        if args.config_file
        else ServiceConfig.get_instance()
    )

    drt = await DistributedRuntime.connect(args.store_host, args.store_port)
    # SIGTERM/SIGINT (the supervisor's stop signal) triggers the graceful
    # path below: deregister endpoints, then close the runtime
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, drt.runtime.shutdown)

    _obj, handles = await serve_service(svc, drt, config)
    try:
        await drt.runtime.wait_shutdown()
    finally:
        for h in handles:
            await h.stop()
        await drt.close()


def main() -> None:
    from ..utils.logging import setup_logging
    setup_logging(logging.INFO)
    asyncio.run(amain(sys.argv[1:]))


if __name__ == "__main__":
    main()
