"""Core streaming-engine abstractions.

``AsyncEngine`` is THE central trait of the framework: everything that turns a
request into a stream of responses — the HTTP frontend's model handles, the
preprocessor/backend pipeline operators, network clients, and the JAX engine
itself — implements it. Mirrors the reference's engine trait surface
(reference: lib/runtime/src/engine.rs:47-145 — AsyncEngine::generate,
AsyncEngineContext id/stop/kill, ResponseStream), re-designed on asyncio.
"""

from __future__ import annotations

import abc
import asyncio
import time
import uuid
from typing import Any, AsyncIterator, Dict, Generic, Optional, TypeVar

T = TypeVar("T")


class AsyncEngineContext:
    """Per-request control handle: identity plus cooperative cancellation.

    ``stop_generating`` asks the producer to finish early but still emit any
    buffered output; ``kill`` demands immediate termination. Both are sticky.

    The context also carries the request's trace: ``trace_id`` is the
    ingress-assigned correlation id (honoring ``X-Request-Id``, so it may
    repeat across requests) while ``id`` stays a per-request unique handle —
    engine and disagg-coordinator state is keyed by ``id``, so a client
    reusing a trace id cannot cross-wire another request's KV transfer or
    first-token future. ``stages`` records (name, monotonic time) span marks
    from every layer the request crosses — HTTP, scheduler
    admission/prefill/first-token, completion. Storing them HERE (not in
    pipeline baggage) means the scheduler, which only holds the
    AsyncEngineContext, can stamp spans too.
    """

    def __init__(self, request_id: Optional[str] = None,
                 trace_id: Optional[str] = None):
        self.id: str = request_id or uuid.uuid4().hex
        self.trace_id: str = trace_id or self.id
        self.stages: list = []  # [(stage_name, time.monotonic())]
        # wall anchor for span EXPORT: monotonic stamps are process-local,
        # so spans that cross a process boundary (the cluster-stitched
        # trace, telemetry/stitch.py) ship as wall-clock times derived
        # from this one (mono, wall) pair
        self._anchor = (time.monotonic(), time.time())
        # span sets collected from downstream processes (dial-back end
        # frames, remote-prefill commits, migration end frames), each a
        # stitch.remote_span_set dict with offsets relative to THIS
        # process's clock
        self.remote_spans: list = []
        self._stopped = asyncio.Event()
        self._killed = asyncio.Event()

    def add_stage(self, name: str) -> None:
        """Record a processing span mark (reference:
        pipeline/context.rs:125 add_stage)."""
        self.stages.append((name, time.monotonic()))

    def wall(self, t_monotonic: float) -> float:
        """Monotonic stamp → this process's wall clock (span export)."""
        return self._anchor[1] + (t_monotonic - self._anchor[0])

    def export_spans(self) -> list:
        """Span marks as ``[name, wall_time]`` pairs — the shape that
        piggybacks on response/commit frames for cross-process
        stitching (telemetry/stitch.py)."""
        return [[name, self.wall(t)] for name, t in self.stages]

    def add_remote_spans(self, span_set: dict) -> None:
        """Attach one downstream hop's folded span set (a
        stitch.remote_span_set dict) to this request's trace."""
        self.remote_spans.append(span_set)

    def merge_stages_from(self, children: list) -> None:
        """Fold per-choice child-context spans into this trace (the n>1 /
        best_of fan-out gives every choice its own context for cancellation
        isolation). Child stage names gain a ``#<choice>`` suffix and the
        combined list stays chronological, so /debug/requests/{id} shows
        engine spans for multi-choice requests too."""
        for i, child in enumerate(children):
            self.stages.extend(
                (f"{name}#{i}", t) for name, t in child.stages
            )
            # a choice served by a remote worker collected that worker's
            # span set — it belongs to the parent trace like the stages
            self.remote_spans.extend(child.remote_spans)
        self.stages.sort(key=lambda s: s[1])

    def stop_generating(self) -> None:
        self._stopped.set()

    def kill(self) -> None:
        self._stopped.set()
        self._killed.set()

    @property
    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    @property
    def is_killed(self) -> bool:
        return self._killed.is_set()

    async def wait_stopped(self) -> None:
        await self._stopped.wait()


class Context(Generic[T]):
    """A request travelling through a pipeline: payload + control + baggage.

    ``baggage`` is a typed-map analog of the reference's per-request Context
    (reference: lib/runtime/src/pipeline/context.rs:33-150) used by operators
    to pass side-channel data (e.g. the preprocessor stashes the tokenized
    prompt for the response path).
    """

    def __init__(
        self,
        payload: T,
        context: Optional[AsyncEngineContext] = None,
        baggage: Optional[Dict[str, Any]] = None,
    ):
        self.payload = payload
        self.context = context or AsyncEngineContext()
        self.baggage: Dict[str, Any] = baggage or {}

    @property
    def id(self) -> str:
        return self.context.id

    @property
    def trace_id(self) -> str:
        return self.context.trace_id

    def add_stage(self, name: str) -> None:
        """Record a processing stage + monotonic timestamp on the request
        (reference: pipeline/context.rs:125 add_stage). Stages live on the
        shared AsyncEngineContext, so they survive ``map`` AND are visible
        to token-level layers (the scheduler) that never see this wrapper;
        the frontend logs/records the per-stage latency breakdown at
        completion (utils/logging.py stage_summary, telemetry/tracing.py)."""
        self.context.add_stage(name)

    @property
    def stages(self):
        return self.context.stages

    def map(self, new_payload: Any) -> "Context[Any]":
        """New payload, same identity/control/baggage."""
        return Context(new_payload, self.context, self.baggage)


class AsyncEngine(abc.ABC):
    """request → async stream of responses. Streaming-first, single method."""

    @abc.abstractmethod
    def generate(self, request: Context[Any]) -> AsyncIterator[Any]:
        """Returns an async iterator of responses for this request."""
        raise NotImplementedError

    async def close(self) -> None:  # optional lifecycle hook
        pass


class EngineError(Exception):
    """Engine could not be created / request rejected before streaming began.

    The network layer maps this onto the response-stream prologue so callers
    get a clean error instead of an empty stream (reference:
    lib/runtime/src/pipeline/network/egress/push.rs ResponseStreamPrologue).
    """


class EngineDrainingError(EngineError):
    """The engine is draining (recovery ladder / rolling update) and takes
    no new work. Transient by construction — the HTTP edge maps it to a
    retryable 503 (vs. EngineError's 400) so load balancers and clients
    re-dispatch to the pool instead of surfacing a client error."""
