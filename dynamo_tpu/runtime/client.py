"""Endpoint client: live instance tracking + routed streaming requests.

Watches the endpoint's discovery prefix into a live instance map and routes
each request per ``RouterMode`` (reference:
lib/runtime/src/component/client.rs:95-319 — watch-backed endpoint set,
random/round_robin/direct/static modes, AsyncEngine impl on the client).
"""

from __future__ import annotations

import asyncio
import enum
import itertools
import logging
import random
import uuid
from typing import Any, AsyncIterator, Dict, Optional

import msgpack

from .component import Endpoint
from .discovery import WatchEventType
from .engine import AsyncEngine, Context
from .network import ResponseReceiver, open_response_stream

logger = logging.getLogger(__name__)


class RouterMode(enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    STATIC = "static"
    KV = "kv"  # resolved by an external KV-aware router, then DIRECT


class NoInstancesError(ConnectionError):
    pass


class Client(AsyncEngine):
    """Streaming client for one endpoint."""

    def __init__(self, endpoint: Endpoint, mode: RouterMode = RouterMode.ROUND_ROBIN,
                 model: Optional[str] = None):
        self.endpoint = endpoint
        self.mode = mode
        # per-model pool filter (registry/): several model pools can
        # share one component endpoint — each instance's registration
        # metadata names the model it serves, and a model-bound client
        # only routes within its pool. Instances registered WITHOUT a
        # model are wildcard-eligible (legacy single-model workers).
        self.model = model
        self.instances: Dict[str, dict] = {}
        self._rr = itertools.count()
        self._watch_task: Optional[asyncio.Task] = None
        self._watcher = None
        self._started = False
        self._instances_changed = asyncio.Event()

    async def start(self) -> "Client":
        """Begin watching the discovery prefix (no-op in static mode)."""
        if self._started:
            return self
        self._started = True
        if self.mode == RouterMode.STATIC:
            return self
        drt = self.endpoint.drt
        prefix = f"{self.endpoint.component.etcd_prefix()}{self.endpoint.name}:"
        snapshot, watcher = await drt.discovery.watch_prefix(prefix)
        for key, value in snapshot.items():
            self._add(key, value)
        self._watcher = watcher
        self._watch_task = drt.runtime.spawn(self._watch_loop(watcher))
        return self

    def _add(self, key: str, value: bytes) -> None:
        try:
            info = msgpack.unpackb(value, raw=False)
        except Exception:
            logger.warning("bad endpoint info at %s", key)
            return
        self.instances[info["instance_id"]] = info
        self._instances_changed.set()

    async def _watch_loop(self, watcher) -> None:
        async for ev in watcher:
            if ev.type == WatchEventType.PUT:
                self._add(ev.key, ev.value)
            else:
                instance_id = ev.key.rsplit(":", 1)[-1]
                self.instances.pop(instance_id, None)
                self._instances_changed.set()

    def instance_ids(self) -> list:
        return sorted(self.instances)

    def eligible_ids(self, model: Optional[str] = None) -> list:
        """Instance ids in the routing pool: all of them for an
        unfiltered client, otherwise those whose registration metadata
        matches the model (missing metadata = wildcard)."""
        model = model if model is not None else self.model
        if model is None:
            return sorted(self.instances)
        return sorted(
            iid for iid, info in self.instances.items()
            if info.get("model") in (None, model)
        )

    async def wait_for_instances(self, n: int = 1, timeout: float = 30.0) -> None:
        async def _wait():
            while len(self.eligible_ids()) < n:
                self._instances_changed.clear()
                await self._instances_changed.wait()

        await asyncio.wait_for(_wait(), timeout)

    # --- routing ---

    def _pick(self, instance_id: Optional[str],
              model: Optional[str] = None) -> str:
        if self.mode == RouterMode.STATIC:
            return "static"
        if instance_id is not None:
            if instance_id not in self.instances:
                raise NoInstancesError(
                    f"instance {instance_id} not found for {self.endpoint.path()}"
                )
            return instance_id
        ids = self.eligible_ids(model)
        if not ids:
            model = model if model is not None else self.model
            pool = f" serving model {model!r}" if model else ""
            raise NoInstancesError(
                f"no instances{pool} for {self.endpoint.path()}")
        if self.mode == RouterMode.RANDOM:
            return random.choice(ids)
        return ids[next(self._rr) % len(ids)]

    async def open_stream(
        self, payload: Any, instance_id: Optional[str] = None,
        trace_id: Optional[str] = None, model: Optional[str] = None,
    ) -> ResponseReceiver:
        """Route, push the request, return the dialed-back response stream.

        ``trace_id`` rides the two-part message header so the worker-side
        engine context (and everything downstream of it — scheduler spans,
        remote-prefill requests, logs) keeps the ingress-assigned id.
        """
        if not self._started:
            await self.start()
        target = self._pick(instance_id, model)
        drt = self.endpoint.drt
        conn, receiver = await open_response_stream(drt.stream_server, drt.local)
        req_id = uuid.uuid4().hex
        # wire-serialize rich payloads (pydantic models, protocol dataclasses);
        # mode="json" coerces enums/datetimes into msgpack-able primitives
        if hasattr(payload, "model_dump"):
            payload = payload.model_dump(mode="json", exclude_none=True)
        elif hasattr(payload, "to_wire"):
            payload = payload.to_wire()
        header = {"req_id": req_id, "conn": conn}
        if trace_id:
            header["trace_id"] = trace_id
        two_part = {"header": header, "payload": payload}
        # request-send wall time, for the per-hop clock-offset estimate
        # when the worker's end frame ships its spans back
        import time as _time

        receiver.req_sent_at = _time.time()
        await drt.messaging.publish(
            self.endpoint.subject(target), msgpack.packb(two_part, use_bin_type=True)
        )
        return receiver

    async def generate(self, request: Context[Any]) -> AsyncIterator[Any]:
        """AsyncEngine over the network: request context controls propagate."""
        instance_id = request.baggage.get("instance_id")
        receiver = await self.open_stream(
            request.payload, instance_id, trace_id=request.trace_id,
            # the processor stamps the request's model so a shared-
            # endpoint fallback pick (router down / non-KV modes) still
            # lands inside the right pool
            model=request.baggage.get("model_pool"),
        )
        await receiver.wait_prologue()

        # propagate caller-side cancellation to the worker
        async def relay_cancel():
            await request.context.wait_stopped()
            if request.context.is_killed:
                receiver.kill()
            else:
                receiver.stop_generating()

        relay = asyncio.create_task(relay_cancel())
        exhausted = False
        try:
            async for item in receiver:
                yield item
            exhausted = True
        finally:
            relay.cancel()
            if not exhausted and not request.context.is_stopped:
                # caller stopped consuming early. For detokenizing
                # consumers (llm/backend.py) this is the NORMAL end of
                # every stream — they break at the finish chunk, and the
                # worker's end frame (carrying the span export for the
                # stitched trace) is right behind it on the wire. Give
                # the frame pump one bounded beat to deliver it before
                # killing; a genuinely abandoned mid-generation stream
                # just pays 50 ms of extra cancellation latency.
                try:
                    if (receiver.remote_spans is None
                            and receiver._pump_task is not None):
                        try:
                            await asyncio.wait_for(
                                asyncio.shield(receiver._pump_task), 0.05
                            )
                        # dynlint: allow(silent-except) - best-effort grace for the end frame; the finally's kill() is the real cleanup
                        except Exception:
                            pass
                finally:
                    # kill UNCONDITIONALLY — a cancellation escaping the
                    # grace wait (CancelledError is not an Exception)
                    # must not leave the worker generating into a dead
                    # queue; the caller abandoned the stream
                    receiver.kill()
            rs = receiver.remote_spans
            if rs is not None:
                # fold the worker's exported spans into this request's
                # trace with an NTP-style offset estimated from the
                # send/receive wall pairs — the stitched-timeline hop
                from ..telemetry.stitch import remote_span_set

                request.context.add_remote_spans(remote_span_set(
                    rs.get("source", "worker"), rs.get("spans") or [],
                    rs.get("recv_at", 0.0), rs.get("resp_sent_at", 0.0),
                    getattr(receiver, "req_sent_at", 0.0),
                    receiver.resp_recv_at,
                    children=rs.get("children") or [],
                ))

    async def direct(self, payload: Any, instance_id: str) -> ResponseReceiver:
        receiver = await self.open_stream(payload, instance_id)
        await receiver.wait_prologue()
        return receiver

    # --- stats scrape (reference: NATS $SRV.STATS service scrape) ---

    async def scrape_stats(self, timeout: float = 0.5) -> Dict[str, dict]:
        """Ask every live instance for its stats; missing answers are dropped."""
        drt = self.endpoint.drt
        out: Dict[str, dict] = {}

        async def one(iid: str):
            try:
                raw = await drt.messaging.request(
                    f"_stats.{self.endpoint.subject(iid)}", b"", timeout=timeout
                )
                out[iid] = msgpack.unpackb(raw, raw=False)
            except Exception as e:
                # dropping the answer is the contract; dropping the trace
                # of WHY an instance never answers is not
                logger.debug("stats scrape from %s failed: %s", iid, e)

        await asyncio.gather(*(one(i) for i in self.instance_ids()))
        return out

    async def close(self) -> None:
        if self._watcher is not None:
            self._watcher.cancel()
        if self._watch_task is not None:
            self._watch_task.cancel()
