"""Pipeline composition: operators around a terminal engine.

The reference wires request pipelines as a doubly-linked chain of nodes
(frontend → operator forward edges → engine → operator backward edges →
frontend; reference: lib/runtime/src/pipeline/nodes.rs,
launch/dynamo-run/src/input/common.rs:77-100). The idiomatic asyncio
re-design: an ``Operator`` transforms the request on the way in and the
response stream on the way out, and ``build_pipeline`` composes operators
middleware-style into a single ``AsyncEngine``. A composed pipeline can be
served over the network (``Endpoint.serve``) or called in-process — the
segment source/sink split falls out for free because ``Client`` is itself
an ``AsyncEngine``.
"""

from __future__ import annotations

import abc
from typing import Any, AsyncIterator, Sequence

from .engine import AsyncEngine, Context


class Operator(abc.ABC):
    """Bidirectional request/response transform."""

    @abc.abstractmethod
    def generate(self, request: Context[Any], next_engine: AsyncEngine) -> AsyncIterator[Any]:
        """Transform request, call ``next_engine``, transform its stream."""


class _OperatorEngine(AsyncEngine):
    def __init__(self, operator: Operator, next_engine: AsyncEngine):
        self.operator = operator
        self.next_engine = next_engine

    def generate(self, request: Context[Any]) -> AsyncIterator[Any]:
        return self.operator.generate(request, self.next_engine)

    async def close(self) -> None:
        await self.next_engine.close()


def build_pipeline(operators: Sequence[Operator], engine: AsyncEngine) -> AsyncEngine:
    """Compose ``operators`` (outermost first) around ``engine``."""
    current = engine
    for op in reversed(list(operators)):
        current = _OperatorEngine(op, current)
    return current
