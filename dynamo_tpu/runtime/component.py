"""Distributed runtime and the Namespace → Component → Endpoint model.

Naming scheme (mirrors the reference's etcd/NATS layout, reference:
lib/runtime/src/component.rs:104-345):

  discovery key : {ns}/components/{comp}/endpoints/{ep}:{lease_id_hex}
  subject       : {ns}.{comp}.{ep}.{lease_id_hex}       (instance push)
  static subject: {ns}.{comp}.{ep}.static               (no-discovery mode)

A serving endpoint = a queue subscription on its instance subject + a
discovery key attached to the worker's primary lease. Lease expiry (worker
death) deletes the key; clients watching the prefix drop the instance.
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any, AsyncIterator, Callable, Dict, Optional, Union

import msgpack

from .discovery import DiscoveryClient, WatchEventType
from .engine import AsyncEngine, AsyncEngineContext, Context, EngineError
from .messaging import MessagingClient
from .network import StreamServer, respond_to

logger = logging.getLogger(__name__)

# Handler signature: async generator over response payloads.
Handler = Callable[[Any, AsyncEngineContext], AsyncIterator[Any]]


class Runtime:
    """Process-level runtime: identity + root cancellation + task tracking."""

    def __init__(self) -> None:
        self.worker_id: str = uuid.uuid4().hex[:16]
        self._shutdown = asyncio.Event()
        self._tasks: set = set()

    def spawn(self, coro) -> asyncio.Task:
        task = asyncio.create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    def shutdown(self) -> None:
        self._shutdown.set()
        for task in list(self._tasks):
            task.cancel()

    async def wait_shutdown(self) -> None:
        await self._shutdown.wait()


class DistributedRuntime:
    """Runtime + the two planes + the process's dial-back stream server.

    ``local`` means single-process mode: requester and workers share the
    process, so response streams use in-memory queues instead of TCP.
    """

    def __init__(
        self,
        discovery: DiscoveryClient,
        messaging: MessagingClient,
        runtime: Optional[Runtime] = None,
        local: bool = False,
        advertise_host: str = "127.0.0.1",
    ):
        self.runtime = runtime or Runtime()
        self.discovery = discovery
        self.messaging = messaging
        self.local = local
        self.stream_server = StreamServer(advertise_host=advertise_host)

    @classmethod
    def in_process(cls, hub=None) -> "DistributedRuntime":
        """Single-process runtime over the in-memory hub (tests, `in=http out=jax`)."""
        from .transports.memory import MemoryDiscoveryClient, MemoryMessagingClient, default_hub

        hub = hub or default_hub()
        return cls(
            MemoryDiscoveryClient(hub), MemoryMessagingClient(hub), local=True
        )

    @classmethod
    async def connect(
        cls, host: str = "127.0.0.1", port: Optional[int] = None,
        advertise_host: str = "127.0.0.1",
    ) -> "DistributedRuntime":
        """Multi-process runtime against a dynstore server."""
        from .transports.dynstore import DEFAULT_PORT, DynStoreClient

        client = DynStoreClient(host, port or DEFAULT_PORT)
        await client.connect()
        return cls(client, client, local=False, advertise_host=advertise_host)

    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)

    async def close(self) -> None:
        self.runtime.shutdown()
        await self.stream_server.close()
        await self.discovery.close()
        if self.messaging is not self.discovery:
            await self.messaging.close()


class Namespace:
    def __init__(self, drt: DistributedRuntime, name: str):
        self.drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self, name)

    # --- namespace-scoped events (reference: lib/runtime/src/traits/events.rs) ---

    def event_subject(self, name: str) -> str:
        return f"{self.name}._events.{name}"

    async def publish_event(self, name: str, data: Any) -> None:
        await self.drt.messaging.publish(
            self.event_subject(name), msgpack.packb(data, use_bin_type=True)
        )

    async def subscribe_event(self, name: str):
        return await self.drt.messaging.subscribe(self.event_subject(name))


class Component:
    def __init__(self, namespace: Namespace, name: str):
        self.namespace = namespace
        self.name = name

    @property
    def drt(self) -> DistributedRuntime:
        return self.namespace.drt

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)

    def etcd_prefix(self) -> str:
        return f"{self.namespace.name}/components/{self.name}/endpoints/"

    def event_subject(self, name: str) -> str:
        return f"{self.namespace.name}.{self.name}._events.{name}"

    async def publish_event(self, name: str, data: Any) -> None:
        await self.drt.messaging.publish(
            self.event_subject(name), msgpack.packb(data, use_bin_type=True)
        )

    async def subscribe_event(self, name: str):
        return await self.drt.messaging.subscribe(self.event_subject(name))


class Endpoint:
    def __init__(self, component: Component, name: str):
        self.component = component
        self.name = name

    @property
    def drt(self) -> DistributedRuntime:
        return self.component.drt

    def etcd_key(self, instance_id: str) -> str:
        return f"{self.component.etcd_prefix()}{self.name}:{instance_id}"

    def subject(self, instance_id: str) -> str:
        ns = self.component.namespace.name
        return f"{ns}.{self.component.name}.{self.name}.{instance_id}"

    def path(self) -> str:
        """dyn://ns.comp.ep address of this endpoint."""
        return f"dyn://{self.component.namespace.name}.{self.component.name}.{self.name}"

    async def serve(
        self,
        handler: Union[AsyncEngine, Handler],
        instance_id: Optional[str] = None,
        static: bool = False,
        metadata: Optional[dict] = None,
        stats_handler: Optional[Callable[[], dict]] = None,
        span_source: str = "worker",
    ) -> "ServingEndpoint":
        """Register this endpoint and start consuming requests.

        Returns a handle; requests are handled concurrently until stopped.
        In dynamic mode the instance is discoverable and lease-scoped; in
        static mode there is no discovery (reference: is_static runtimes).
        """
        drt = self.drt
        if static:
            instance_id = "static"
            lease = None
        else:
            lease = await drt.discovery.primary_lease()
            instance_id = instance_id or f"{lease.id:x}-{drt.runtime.worker_id[:8]}"

        subject = self.subject(instance_id)
        sub = await drt.messaging.service_subscribe(subject, queue_group=subject)

        serving = ServingEndpoint(self, instance_id, subject, sub, handler,
                                  stats_handler, span_source=span_source)
        serving.task = drt.runtime.spawn(serving._consume())

        # stats RPC subject (metrics scraping; reference scrapes NATS $SRV.STATS)
        stats_sub = await drt.messaging.subscribe(f"_stats.{subject}")
        serving.stats_task = drt.runtime.spawn(serving._serve_stats(stats_sub))

        if not static:
            info = {
                "instance_id": instance_id,
                "subject": subject,
                "worker_id": drt.runtime.worker_id,
                **(metadata or {}),
            }
            created = await drt.discovery.kv_create(
                self.etcd_key(instance_id),
                msgpack.packb(info, use_bin_type=True),
                lease_id=lease.id,
            )
            if not created:
                # the existing key belongs to another live instance — clean up
                # our half-started serving without touching their registration
                await serving.stop()
                raise RuntimeError(f"endpoint instance already registered: {instance_id}")
            serving.registered = True
        return serving


class ServingEndpoint:
    """A live endpoint consuming its subject; tracks in-flight requests."""

    def __init__(self, endpoint, instance_id, subject, subscription, handler,
                 stats_handler=None, span_source: str = "worker"):
        self.endpoint = endpoint
        self.instance_id = instance_id
        self.subject = subject
        self.subscription = subscription
        self.handler = handler
        self.stats_handler = stats_handler
        # how this process names itself in cluster-stitched traces
        # (telemetry/stitch.py): "decode_engine" for token-level engine
        # workers, "processor" for the router hop, "worker" otherwise
        self.span_source = span_source
        self.task: Optional[asyncio.Task] = None
        self.stats_task: Optional[asyncio.Task] = None
        self.inflight = 0
        self.requests_total = 0
        self.registered = False  # discovery key successfully created

    async def _consume(self) -> None:
        drt = self.endpoint.drt
        async for msg in self.subscription:
            try:
                two_part = msgpack.unpackb(msg.payload, raw=False)
                header = two_part["header"]
                payload = two_part["payload"]
            except Exception:
                logger.exception("malformed request on %s", self.subject)
                continue
            drt.runtime.spawn(self._handle_one(header, payload))

    async def _handle_one(self, header: dict, payload: Any) -> None:
        self.inflight += 1
        self.requests_total += 1
        try:
            def stream_fn(ctx: AsyncEngineContext) -> AsyncIterator[Any]:
                if isinstance(self.handler, AsyncEngine):
                    return self.handler.generate(Context(payload, ctx))
                return self.handler(payload, ctx)

            # req_id (a fresh per-hop UUID) becomes the worker-side engine
            # context id — it keys engine/disagg state, so it must be
            # unique; the ingress-assigned trace id (e.g. X-Request-Id)
            # rides alongside for span/log correlation end to end
            await respond_to(
                header["conn"], stream_fn,
                header.get("req_id", "?"),
                trace_id=header.get("trace_id"),
                span_source=self.span_source,
            )
        finally:
            self.inflight -= 1

    async def _serve_stats(self, stats_sub) -> None:
        drt = self.endpoint.drt
        async for msg in stats_sub:
            if msg.reply:
                stats = {
                    "instance_id": self.instance_id,
                    "subject": self.subject,
                    "inflight": self.inflight,
                    "requests_total": self.requests_total,
                }
                if self.stats_handler is not None:
                    try:
                        stats["data"] = self.stats_handler()
                    except Exception:
                        logger.exception("stats handler failed")
                await drt.messaging.publish(
                    msg.reply, msgpack.packb(stats, use_bin_type=True)
                )

    async def stop(self) -> None:
        self.subscription.cancel()
        if self.stats_task:
            self.stats_task.cancel()
        if self.task:
            self.task.cancel()
        drt = self.endpoint.drt
        if self.registered:
            self.registered = False
            try:
                await drt.discovery.kv_delete(self.endpoint.etcd_key(self.instance_id))
            except Exception as e:
                # shutdown proceeds regardless, but a failed deregistration
                # leaves a ghost instance for routers until the lease lapses
                # — that is worth a line in the log, not silence
                logger.warning("deregistering %s failed (instance stays "
                               "visible until its lease expires): %s",
                               self.endpoint.path(), e)
