"""Distributed runtime core (see SURVEY.md §2.1 for the reference analog)."""

from .client import Client, NoInstancesError, RouterMode
from .component import Component, DistributedRuntime, Endpoint, Namespace, Runtime
from .discovery import DiscoveryClient, Lease, WatchEvent, WatchEventType
from .engine import AsyncEngine, AsyncEngineContext, Context, EngineError
from .messaging import Message, MessagingClient, WorkItem
from .network import ResponseStreamError
from .pipeline import Operator, build_pipeline

__all__ = [
    "AsyncEngine",
    "AsyncEngineContext",
    "Client",
    "Component",
    "Context",
    "DiscoveryClient",
    "DistributedRuntime",
    "Endpoint",
    "EngineError",
    "Lease",
    "Message",
    "MessagingClient",
    "Namespace",
    "NoInstancesError",
    "Operator",
    "ResponseStreamError",
    "RouterMode",
    "Runtime",
    "WatchEvent",
    "WatchEventType",
    "WorkItem",
    "build_pipeline",
]
