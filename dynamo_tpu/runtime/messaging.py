"""Message plane: subject-based pub/sub, queue-group services, work queues.

The role NATS (+JetStream) plays in the reference (reference:
lib/runtime/src/transports/nats.rs:45-130; prefill work queue
examples/llm/utils/nats_queue.py:27-155). Subjects are dot-separated
strings; subscriptions may use a trailing ``*`` wildcard segment.

Three delivery modes:
- ``subscribe``   — fan-out: every subscriber gets every message (KV events,
                    hit-rate events, metrics).
- ``service``     — queue group: each message goes to exactly one member
                    (request push to a worker endpoint).
- ``work_queue``  — durable-ish FIFO with explicit ack and visibility
                    timeout (disaggregated prefill queue). Un-acked items
                    are redelivered — a prefill worker dying mid-job must
                    not lose the job.
"""

from __future__ import annotations

import abc
import asyncio
import dataclasses
from typing import AsyncIterator, Callable, Optional


@dataclasses.dataclass
class Message:
    subject: str
    payload: bytes
    reply: Optional[str] = None


class Subscription:
    """Async stream of Messages; cancel() to stop.

    ``on_cancel`` lets the owning transport release server-side state
    (unsub RPC, registry pruning) when the consumer goes away.
    """

    def __init__(self, on_cancel: Optional[Callable[[], None]] = None) -> None:
        self._queue: asyncio.Queue = asyncio.Queue()
        self._cancelled = False
        self._on_cancel = on_cancel

    def _emit(self, msg: Message) -> None:
        if not self._cancelled:
            self._queue.put_nowait(msg)

    def cancel(self) -> None:
        if self._cancelled:
            return
        self._cancelled = True
        self._queue.put_nowait(None)
        if self._on_cancel is not None:
            self._on_cancel()

    def __aiter__(self) -> AsyncIterator[Message]:
        return self

    async def __anext__(self) -> Message:
        msg = await self._queue.get()
        if msg is None:
            raise StopAsyncIteration
        return msg


@dataclasses.dataclass
class WorkItem:
    payload: bytes
    ack: Callable[[], None]  # call to mark done; otherwise redelivered


class MessagingClient(abc.ABC):
    @abc.abstractmethod
    async def publish(self, subject: str, payload: bytes) -> None:
        pass

    @abc.abstractmethod
    async def subscribe(self, subject: str) -> Subscription:
        """Fan-out subscription. Trailing ``*`` matches one segment."""

    @abc.abstractmethod
    async def service_subscribe(self, subject: str, queue_group: str) -> Subscription:
        """Queue-group subscription: one member of the group per message."""

    @abc.abstractmethod
    async def request(self, subject: str, payload: bytes, timeout: float = 30.0) -> bytes:
        """RPC convenience: publish with reply subject, await one response."""

    # --- work queue (JetStream analog) ---

    @abc.abstractmethod
    async def queue_push(self, queue: str, payload: bytes) -> None:
        pass

    @abc.abstractmethod
    async def queue_pop(
        self, queue: str, timeout: Optional[float] = None, visibility: float = 60.0
    ) -> Optional[WorkItem]:
        """Blocking pop; item is redelivered if not acked within ``visibility``."""

    @abc.abstractmethod
    async def queue_depth(self, queue: str) -> int:
        pass

    async def close(self) -> None:
        pass


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS-style match: ``a.b.*`` matches one segment, ``a.>`` matches rest."""
    if pattern == subject:
        return True
    p_parts = pattern.split(".")
    s_parts = subject.split(".")
    for i, p in enumerate(p_parts):
        if p == ">":
            # NATS semantics: '>' requires at least one more subject token
            return i < len(s_parts)
        if i >= len(s_parts):
            return False
        if p == "*":
            continue
        if p != s_parts[i]:
            return False
    return len(p_parts) == len(s_parts)
