"""In-process discovery + messaging transport.

The default for single-process serving and the test harness — the analog of
the reference's mock network (reference: lib/runtime/tests/common/mock.rs:
30-120, in-memory control/data plane with optional latency injection).
A ``MemoryHub`` is the shared broker; every client in the process points at
the same hub instance.
"""

from __future__ import annotations

import asyncio
import itertools
import random
import time
from typing import Dict, List, Optional, Set, Tuple

from ..discovery import (
    DiscoveryClient,
    Lease,
    PrefixWatcher,
    WatchEvent,
    WatchEventType,
)
from ..messaging import (
    Message,
    MessagingClient,
    Subscription,
    WorkItem,
    subject_matches,
)


class LatencyModel:
    """Optional injected delay, mirroring the reference mock's NoDelay /
    Constant / NormalDistribution latency models."""

    def __init__(self, constant: float = 0.0, jitter: float = 0.0):
        self.constant = constant
        self.jitter = jitter

    async def delay(self) -> None:
        d = self.constant + (random.random() * self.jitter if self.jitter else 0.0)
        if d > 0:
            await asyncio.sleep(d)


class MemoryHub:
    """Shared in-process broker state for both planes."""

    def __init__(self, latency: Optional[LatencyModel] = None):
        self.latency = latency or LatencyModel()
        # discovery
        self.kv: Dict[str, Tuple[bytes, Optional[int]]] = {}  # key -> (value, lease)
        self.leases: Dict[int, Set[str]] = {}  # lease id -> keys
        self.watchers: List[Tuple[str, PrefixWatcher]] = []
        self._lease_ids = itertools.count(1)
        # messaging
        self.subscriptions: List[Tuple[str, Subscription]] = []
        self.groups: Dict[Tuple[str, str], List[Subscription]] = {}
        self._group_rr: Dict[Tuple[str, str], int] = {}
        # work queues
        self.queues: Dict[str, asyncio.Queue] = {}
        self.inflight: Dict[str, Dict[int, bytes]] = {}
        self._item_ids = itertools.count(1)

    # --- discovery internals ---

    def _emit_watch(self, ev: WatchEvent) -> None:
        self.watchers = [(p, w) for p, w in self.watchers if not w._cancelled]
        for prefix, watcher in list(self.watchers):
            if ev.key.startswith(prefix):
                watcher._emit(ev)

    def deliver(self, subject: str, payload: bytes, reply: Optional[str] = None) -> int:
        """Fan-out + queue-group delivery; prunes cancelled subscriptions.
        Returns the number of subscribers the message reached."""
        msg = Message(subject=subject, payload=payload, reply=reply)
        delivered = 0
        self.subscriptions = [
            (p, s) for p, s in self.subscriptions if not s._cancelled
        ]
        for pattern, sub in list(self.subscriptions):
            if subject_matches(pattern, subject):
                sub._emit(msg)
                delivered += 1
        for key, members in list(self.groups.items()):
            pattern, _group = key
            live = [m for m in members if not m._cancelled]
            if len(live) != len(members):
                if live:
                    self.groups[key] = live
                else:
                    del self.groups[key]
                    continue
            if not live or not subject_matches(pattern, subject):
                continue
            idx = self._group_rr.get(key, 0) % len(live)
            self._group_rr[key] = idx + 1
            live[idx]._emit(msg)
            delivered += 1
        return delivered

    def expire_lease(self, lease_id: int) -> None:
        """Simulate worker death: drop all keys attached to the lease."""
        for key in sorted(self.leases.pop(lease_id, set())):
            val = self.kv.pop(key, (b"", None))[0]
            self._emit_watch(WatchEvent(WatchEventType.DELETE, key, val))

    def queue(self, name: str) -> asyncio.Queue:
        if name not in self.queues:
            self.queues[name] = asyncio.Queue()
            self.inflight[name] = {}
        return self.queues[name]


_default_hub: Optional[MemoryHub] = None


def default_hub() -> MemoryHub:
    global _default_hub
    if _default_hub is None:
        _default_hub = MemoryHub()
    return _default_hub


def reset_default_hub() -> None:
    global _default_hub
    _default_hub = None


class MemoryDiscoveryClient(DiscoveryClient):
    def __init__(self, hub: Optional[MemoryHub] = None):
        self.hub = hub or default_hub()
        self._primary_lease: Optional[Lease] = None

    async def grant_lease(self, ttl: float = 10.0) -> Lease:
        lease_id = next(self.hub._lease_ids)
        self.hub.leases[lease_id] = set()
        return Lease(id=lease_id, ttl=ttl)

    async def revoke_lease(self, lease_id: int) -> None:
        self.hub.expire_lease(lease_id)

    async def kv_create(self, key: str, value: bytes, lease_id: Optional[int] = None) -> bool:
        await self.hub.latency.delay()
        if key in self.hub.kv:
            return False
        await self.kv_put(key, value, lease_id)
        return True

    async def kv_put(self, key: str, value: bytes, lease_id: Optional[int] = None) -> None:
        self.hub.kv[key] = (value, lease_id)
        if lease_id is not None:
            self.hub.leases.setdefault(lease_id, set()).add(key)
        self.hub._emit_watch(WatchEvent(WatchEventType.PUT, key, value))

    async def kv_get(self, key: str) -> Optional[bytes]:
        entry = self.hub.kv.get(key)
        return entry[0] if entry else None

    async def kv_get_prefix(self, prefix: str) -> Dict[str, bytes]:
        return {k: v for k, (v, _) in self.hub.kv.items() if k.startswith(prefix)}

    async def kv_delete(self, key: str) -> None:
        entry = self.hub.kv.pop(key, None)
        if entry is not None:
            value, lease_id = entry
            if lease_id is not None and lease_id in self.hub.leases:
                self.hub.leases[lease_id].discard(key)
            self.hub._emit_watch(WatchEvent(WatchEventType.DELETE, key, value))

    async def watch_prefix(self, prefix: str):
        snapshot = await self.kv_get_prefix(prefix)
        watcher = PrefixWatcher()
        self.hub.watchers.append((prefix, watcher))
        return snapshot, watcher


class MemoryMessagingClient(MessagingClient):
    def __init__(self, hub: Optional[MemoryHub] = None):
        self.hub = hub or default_hub()
        self._reply_ids = itertools.count(1)

    async def publish(self, subject: str, payload: bytes) -> None:
        await self.hub.latency.delay()
        self.hub.deliver(subject, payload)

    async def subscribe(self, subject: str) -> Subscription:
        sub = Subscription()
        self.hub.subscriptions.append((subject, sub))
        return sub

    async def service_subscribe(self, subject: str, queue_group: str) -> Subscription:
        sub = Subscription()
        self.hub.groups.setdefault((subject, queue_group), []).append(sub)
        return sub

    async def request(self, subject: str, payload: bytes, timeout: float = 30.0) -> bytes:
        reply_subject = f"_inbox.{id(self)}.{next(self._reply_ids)}"
        reply_sub = await self.subscribe(reply_subject)
        try:
            if self.hub.deliver(subject, payload, reply=reply_subject) == 0:
                raise ConnectionError(f"no responders on subject {subject!r}")
            resp = await asyncio.wait_for(reply_sub.__anext__(), timeout)
            return resp.payload
        finally:
            reply_sub.cancel()

    async def queue_push(self, queue: str, payload: bytes) -> None:
        self.hub.queue(queue).put_nowait(payload)

    async def queue_pop(
        self, queue: str, timeout: Optional[float] = None, visibility: float = 60.0
    ) -> Optional[WorkItem]:
        q = self.hub.queue(queue)
        try:
            if timeout is None:
                payload = await q.get()
            else:
                payload = await asyncio.wait_for(q.get(), timeout)
        except asyncio.TimeoutError:
            return None
        item_id = next(self.hub._item_ids)
        self.hub.inflight[queue][item_id] = payload
        loop = asyncio.get_running_loop()

        def _redeliver():
            pending = self.hub.inflight[queue].pop(item_id, None)
            if pending is not None:
                q.put_nowait(pending)

        handle = loop.call_later(visibility, _redeliver)

        def ack():
            handle.cancel()
            self.hub.inflight[queue].pop(item_id, None)

        return WorkItem(payload=payload, ack=ack)

    async def queue_depth(self, queue: str) -> int:
        return self.hub.queue(queue).qsize()
