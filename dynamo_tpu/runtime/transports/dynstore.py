"""dynstore: the framework's control+message plane server and client.

One asyncio TCP server provides both planes the reference gets from two
external services (reference: etcd for lease-KV-watch discovery,
lib/runtime/src/transports/etcd.rs; NATS for subject pub/sub, queue-group
request push and the JetStream prefill work queue,
lib/runtime/src/transports/nats.rs, examples/llm/utils/nats_queue.py).
The environment ships no etcd or NATS, so the framework carries its own:
semantics match (transactional create, prefix watch with Put/Delete, lease
TTL liveness, queue groups, ack/visibility work queues), implementation is
ours.

Wire protocol: 4-byte big-endian length, then a msgpack map. Requests carry
``id`` for RPC correlation; server pushes carry ``push`` with a watcher /
subscription id. One TCP connection per client, multiplexed.

Run standalone:  python -m dynamo_tpu.runtime.transports.dynstore --port 4871
"""

from __future__ import annotations

import argparse
import asyncio
import itertools
import logging
import time
from typing import Dict, Optional, Set, Tuple

import msgpack

from ..discovery import (
    DiscoveryClient,
    Lease,
    PrefixWatcher,
    WatchEvent,
    WatchEventType,
)
from ..messaging import Message, MessagingClient, Subscription, WorkItem, subject_matches

logger = logging.getLogger(__name__)

DEFAULT_PORT = 4871


async def read_frame(reader: asyncio.StreamReader) -> Optional[dict]:
    try:
        header = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    length = int.from_bytes(header, "big")
    try:
        body = await reader.readexactly(length)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    try:
        return msgpack.unpackb(body, raw=False)
    except Exception:
        logger.warning("dropping undecodable %d-byte frame", length)
        return None


def write_frame(writer: asyncio.StreamWriter, obj: dict) -> None:
    body = msgpack.packb(obj, use_bin_type=True)
    writer.write(len(body).to_bytes(4, "big") + body)


class _ServerConn:
    """Per-connection server state."""

    def __init__(self, server: "DynStoreServer", writer: asyncio.StreamWriter):
        self.server = server
        self.writer = writer
        self.leases: Set[int] = set()
        self.watch_ids: Set[int] = set()
        self.sub_ids: Set[int] = set()
        self.send_lock = asyncio.Lock()
        self.closed = False

    async def send(self, obj: dict) -> None:
        if self.closed:
            return
        try:
            async with self.send_lock:
                write_frame(self.writer, obj)
                await self.writer.drain()
        except (ConnectionResetError, BrokenPipeError, RuntimeError):
            self.closed = True


class DynStoreServer:
    """The broker process: lease-KV-watch + pub/sub + work queues."""

    def __init__(self, host: str = "127.0.0.1", port: int = DEFAULT_PORT):
        self.host = host
        self.port = port
        # kv: key -> (value, lease_id)
        self.kv: Dict[str, Tuple[bytes, Optional[int]]] = {}
        # leases: id -> (expiry_time, ttl, keys)
        self.leases: Dict[int, Tuple[float, float, Set[str]]] = {}
        # watches: wid -> (prefix, conn)
        self.watches: Dict[int, Tuple[str, _ServerConn]] = {}
        # subs: sid -> (pattern, group | None, conn)
        self.subs: Dict[int, Tuple[str, Optional[str], _ServerConn]] = {}
        self._group_rr: Dict[Tuple[str, str], int] = {}
        self.queues: Dict[str, asyncio.Queue] = {}
        self.inflight: Dict[str, Dict[int, bytes]] = {}
        self._ids = itertools.count(1)
        self._server: Optional[asyncio.AbstractServer] = None
        self._reaper_task: Optional[asyncio.Task] = None
        self._conns: set = set()
        self._op_tasks: set = set()

    # --- lifecycle ---

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        self._reaper_task = asyncio.create_task(self._reap_leases())
        logger.info("dynstore listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._reaper_task:
            self._reaper_task.cancel()
        # drop live client connections first: Server.wait_closed() (py3.12)
        # otherwise blocks until every connected client hangs up on its own
        for conn in list(self._conns):
            conn.closed = True
            conn.writer.close()
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    async def serve_forever(self) -> None:
        await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    # --- lease liveness ---

    async def _reap_leases(self) -> None:
        while True:
            await asyncio.sleep(0.5)
            now = time.monotonic()
            expired = [lid for lid, (exp, _, _) in self.leases.items() if exp < now]
            for lid in expired:
                await self._expire_lease(lid)

    async def _expire_lease(self, lease_id: int) -> None:
        entry = self.leases.pop(lease_id, None)
        if entry is None:
            return
        _, _, keys = entry
        for key in sorted(keys):
            await self._delete_key(key)

    async def _delete_key(self, key: str) -> None:
        entry = self.kv.pop(key, None)
        if entry is None:
            return
        value, lease_id = entry
        if lease_id is not None and lease_id in self.leases:
            self.leases[lease_id][2].discard(key)
        await self._emit_watch(WatchEventType.DELETE, key, value)

    async def _emit_watch(self, ev_type: WatchEventType, key: str, value: bytes) -> None:
        for wid, (prefix, conn) in list(self.watches.items()):
            if key.startswith(prefix):
                await conn.send(
                    {"push": "watch", "wid": wid, "type": ev_type.value, "key": key, "value": value}
                )

    # --- connection handling ---

    async def _handle(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        conn = _ServerConn(self, writer)
        self._conns.add(conn)
        try:
            while True:
                req = await read_frame(reader)
                if req is None:
                    break
                # each op handled concurrently so a blocking queue_pop doesn't
                # stall keepalives on the same connection; keep a strong ref
                # (bare create_task results are GC-able mid-flight)
                task = asyncio.create_task(self._dispatch(conn, req))
                self._op_tasks.add(task)
                task.add_done_callback(self._op_tasks.discard)
        finally:
            conn.closed = True
            self._conns.discard(conn)
            await self._cleanup_conn(conn)
            writer.close()

    async def _cleanup_conn(self, conn: _ServerConn) -> None:
        """Connection death == worker death: expire its leases immediately."""
        for wid in list(conn.watch_ids):
            self.watches.pop(wid, None)
        for sid in list(conn.sub_ids):
            self.subs.pop(sid, None)
        for lid in list(conn.leases):
            await self._expire_lease(lid)

    async def _dispatch(self, conn: _ServerConn, req: dict) -> None:
        op = req.get("op")
        rid = req.get("id")
        try:
            result = await self._execute(conn, op, req)
            if rid is not None:
                await conn.send({"id": rid, "ok": True, **(result or {})})
        except Exception as e:  # report, don't kill the connection
            logger.exception("dynstore op %s failed", op)
            if rid is not None:
                await conn.send({"id": rid, "ok": False, "error": str(e)})

    async def _execute(self, conn: _ServerConn, op: str, req: dict) -> Optional[dict]:
        if op == "lease_grant":
            lid = next(self._ids)
            ttl = float(req.get("ttl", 10.0))
            self.leases[lid] = (time.monotonic() + ttl, ttl, set())
            conn.leases.add(lid)
            return {"lease": lid, "ttl": ttl}
        if op == "lease_keepalive":
            lid = req["lease"]
            if lid in self.leases:
                _, ttl, keys = self.leases[lid]
                self.leases[lid] = (time.monotonic() + ttl, ttl, keys)
                return {"alive": True}
            return {"alive": False}
        if op == "lease_revoke":
            await self._expire_lease(req["lease"])
            conn.leases.discard(req["lease"])
            return {}
        if op == "kv_create":
            if req["key"] in self.kv:
                return {"created": False}
            await self._kv_put(req["key"], req["value"], req.get("lease"))
            return {"created": True}
        if op == "kv_put":
            await self._kv_put(req["key"], req["value"], req.get("lease"))
            return {}
        if op == "kv_get":
            entry = self.kv.get(req["key"])
            return {"value": entry[0] if entry else None}
        if op == "kv_get_prefix":
            pfx = req["prefix"]
            return {"kvs": {k: v for k, (v, _) in self.kv.items() if k.startswith(pfx)}}
        if op == "kv_delete":
            await self._delete_key(req["key"])
            return {}
        if op == "watch":
            wid = next(self._ids)
            self.watches[wid] = (req["prefix"], conn)
            conn.watch_ids.add(wid)
            pfx = req["prefix"]
            return {"wid": wid, "kvs": {k: v for k, (v, _) in self.kv.items() if k.startswith(pfx)}}
        if op == "unwatch":
            self.watches.pop(req["wid"], None)
            conn.watch_ids.discard(req["wid"])
            return {}
        if op == "sub":
            sid = next(self._ids)
            self.subs[sid] = (req["subject"], req.get("group"), conn)
            conn.sub_ids.add(sid)
            return {"sid": sid}
        if op == "unsub":
            self.subs.pop(req["sid"], None)
            conn.sub_ids.discard(req["sid"])
            return {}
        if op == "pub":
            delivered = await self._publish(req["subject"], req["payload"], req.get("reply"))
            return {"delivered": delivered}
        if op == "queue_push":
            self._queue(req["queue"]).put_nowait(req["payload"])
            return {}
        if op == "queue_pop":
            return await self._queue_pop(conn, req)
        if op == "queue_ack":
            self.inflight.get(req["queue"], {}).pop(req["item"], None)
            return {}
        if op == "queue_depth":
            return {"depth": self._queue(req["queue"]).qsize()}
        if op == "ping":
            return {"pong": True}
        raise ValueError(f"unknown op {op!r}")

    async def _kv_put(self, key: str, value: bytes, lease_id: Optional[int]) -> None:
        if lease_id is not None and lease_id not in self.leases:
            raise ValueError(f"lease {lease_id} does not exist")
        self.kv[key] = (value, lease_id)
        if lease_id is not None:
            self.leases[lease_id][2].add(key)
        await self._emit_watch(WatchEventType.PUT, key, value)

    async def _publish(self, subject: str, payload: bytes, reply: Optional[str]) -> int:
        delivered = 0
        groups_seen: Dict[Tuple[str, str], list] = {}
        for sid, (pattern, group, conn) in list(self.subs.items()):
            if conn.closed or not subject_matches(pattern, subject):
                continue
            if group is None:
                await conn.send(
                    {"push": "msg", "sid": sid, "subject": subject, "payload": payload, "reply": reply}
                )
                delivered += 1
            else:
                groups_seen.setdefault((pattern, group), []).append((sid, conn))
        for key, members in groups_seen.items():
            idx = self._group_rr.get(key, 0) % len(members)
            self._group_rr[key] = idx + 1
            sid, conn = members[idx]
            await conn.send(
                {"push": "msg", "sid": sid, "subject": subject, "payload": payload, "reply": reply}
            )
            delivered += 1
        return delivered

    def _queue(self, name: str) -> asyncio.Queue:
        if name not in self.queues:
            self.queues[name] = asyncio.Queue()
            self.inflight[name] = {}
        return self.queues[name]

    async def _queue_pop(self, conn: _ServerConn, req: dict) -> dict:
        q = self._queue(req["queue"])
        timeout = req.get("timeout")
        try:
            if timeout is None:
                payload = await q.get()
            else:
                payload = await asyncio.wait_for(q.get(), timeout)
        except asyncio.TimeoutError:
            return {"payload": None}
        if conn.closed:
            # popper died while blocked — hand the job straight back instead
            # of parking it invisible for the full visibility window
            q.put_nowait(payload)
            return {"payload": None}
        item_id = next(self._ids)
        qname = req["queue"]
        self.inflight[qname][item_id] = payload
        visibility = float(req.get("visibility", 60.0))
        loop = asyncio.get_running_loop()

        def _redeliver():
            pending = self.inflight[qname].pop(item_id, None)
            if pending is not None:
                q.put_nowait(pending)

        loop.call_later(visibility, _redeliver)
        return {"payload": payload, "item": item_id}


class DynStoreClient(DiscoveryClient, MessagingClient):
    """One client implementing both planes over a single multiplexed TCP conn.

    Survives broker restarts (reference analog: etcd lease
    re-establishment, lib/runtime/src/transports/etcd/lease.rs:19-117):
    on connection loss it reconnects with backoff and restores the whole
    session — leases are re-granted (their *client-side* ids are stable,
    so lease-derived endpoint keys/subjects don't change), lease-attached
    keys are re-put, prefix watches re-arm (emitting synthetic PUT/DELETE
    events for whatever changed while detached), and subscriptions
    re-subscribe. In-flight RPCs at the moment of loss still fail; new
    RPCs block until the session is back (up to ``max_reconnect_wait``).

    Scope note: only *lease-attached* keys are restored — they are this
    client's ephemeral registrations. Durable unleased KV lives in the
    broker, which is a single unreplicated process; restart loses it.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = DEFAULT_PORT):
        self.host = host
        self.port = port
        self.reconnect = True          # False restores fail-fast semantics
        self.max_reconnect_wait = 30.0  # how long new RPCs wait for a session
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._watchers: Dict[int, PrefixWatcher] = {}
        self._subs: Dict[int, Subscription] = {}
        # pushes that arrive between the watch/sub RPC response frame and the
        # awaiting coroutine registering its watcher/subscription object
        self._early_pushes: Dict[int, list] = {}
        self._dead_ids: set = set()  # cancelled wids/sids — never buffer these
        self._ids = itertools.count(1)
        self._reader_task: Optional[asyncio.Task] = None
        self._keepalive_tasks: Dict[int, asyncio.Task] = {}
        self._send_lock = asyncio.Lock()
        self._primary_lease: Optional[Lease] = None
        self._closed = False
        self._bg_tasks: set = set()
        # client-lease-handle -> {"server": server lease id, "ttl": float,
        # "keys": {key: value}} — everything needed to rebuild the session
        self._client_leases: Dict[int, Dict] = {}
        self._connected = asyncio.Event()
        self._reconnect_task: Optional[asyncio.Task] = None

    def _spawn_bg(self, coro) -> None:
        """Fire-and-forget RPC with a strong task reference (GC-safe)."""
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)

        def _done(t):
            self._bg_tasks.discard(t)
            if not t.cancelled() and t.exception() is not None:
                logger.debug("background rpc failed: %s", t.exception())

        task.add_done_callback(_done)

    async def connect(self) -> "DynStoreClient":
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        self._reader_task = asyncio.create_task(self._read_loop())
        self._connected.set()
        return self

    async def close(self) -> None:
        self._closed = True
        for t in self._keepalive_tasks.values():
            t.cancel()
        if self._reconnect_task:
            self._reconnect_task.cancel()
        if self._reader_task:
            self._reader_task.cancel()
        if self._writer:
            self._writer.close()

    async def _read_loop(self) -> None:
        assert self._reader is not None
        while True:
            frame = await read_frame(self._reader)
            if frame is None:
                break
            if "push" in frame:
                self._handle_push(frame)
            else:
                fut = self._pending.pop(frame.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(frame)
        # connection lost: fail all in-flight RPCs (their responses are gone)
        self._connected.clear()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("dynstore connection lost"))
        self._pending.clear()
        if self._closed or not self.reconnect:
            for w in self._watchers.values():
                w.cancel()
            for s in self._subs.values():
                s.cancel()
            return
        if self._reconnect_task is None or self._reconnect_task.done():
            self._reconnect_task = asyncio.create_task(self._reconnect_loop())

    async def _reconnect_loop(self) -> None:
        """Re-dial with exponential backoff, then rebuild the session."""
        delay = 0.05
        while not self._closed:
            try:
                self._reader, self._writer = await asyncio.open_connection(
                    self.host, self.port
                )
            except OSError:
                await asyncio.sleep(delay)
                delay = min(delay * 2, 2.0)
                continue
            self._reader_task = asyncio.create_task(self._read_loop())
            try:
                await self._restore_session()
            except (ConnectionError, OSError, RuntimeError, asyncio.TimeoutError) as e:
                logger.warning("dynstore session restore failed, retrying: %s", e)
                self._writer.close()
                await asyncio.sleep(delay)
                delay = min(delay * 2, 2.0)
                continue
            self._connected.set()
            logger.info("dynstore reconnected to %s:%d", self.host, self.port)
            return

    async def _restore_session(self) -> None:
        """Re-grant leases, re-put their keys, re-arm watches and subs.

        Watch re-arm reconciles the broker's current state against what the
        watcher had already seen, emitting synthetic DELETE/PUT events so
        consumers converge without missing transitions.

        Order matters and mirrors initial bring-up (component.py serve):
        subscriptions re-arm BEFORE lease keys re-put — the moment another
        client's watch sees our re-registered endpoint key it may push a
        request at our subject, which must already have its subscriber."""
        # the new broker allocates ids from scratch: stale wid/sid state
        # from the old id space must go first, or a fresh id that collides
        # with an old one gets evicted/dropped by the stale bookkeeping
        live_subs = list(self._subs.values())
        live_watchers = list(self._watchers.values())
        self._subs.clear()
        self._watchers.clear()
        self._early_pushes.clear()
        self._dead_ids.clear()

        for sub in live_subs:
            kwargs = {"group": sub._dyn_group} if sub._dyn_group else {}
            resp = await self._rpc_now("sub", subject=sub._dyn_subject, **kwargs)
            sub._dyn_sid = resp["sid"]
            self._subs[resp["sid"]] = sub
            self._drain_early(resp["sid"])
        for state in self._client_leases.values():
            resp = await self._rpc_now("lease_grant", ttl=state["ttl"])
            state["server"] = resp["lease"]
            for key, value in state["keys"].items():
                await self._rpc_now(
                    "kv_put", key=key, value=value, lease=state["server"]
                )
        for watcher in live_watchers:
            resp = await self._rpc_now("watch", prefix=watcher._dyn_prefix)
            watcher._dyn_wid = resp["wid"]
            self._watchers[resp["wid"]] = watcher
            seen: Dict[str, bytes] = watcher._dyn_seen
            now_kvs: Dict[str, bytes] = resp["kvs"]
            for key in [k for k in seen if k not in now_kvs]:
                watcher._emit(WatchEvent(WatchEventType.DELETE, key, seen.pop(key)))
            for key, value in now_kvs.items():
                if seen.get(key) != value:
                    seen[key] = value
                    watcher._emit(WatchEvent(WatchEventType.PUT, key, value))
            self._drain_early(resp["wid"])

    def _handle_push(self, frame: dict) -> None:
        kind = frame["push"]
        if kind == "watch":
            watcher = self._watchers.get(frame["wid"])
            if watcher is not None:
                ev = WatchEvent(
                    WatchEventType(frame["type"]), frame["key"], frame["value"]
                )
                # track what the consumer has seen so a reconnect can
                # reconcile (synthetic events for the detached window)
                if ev.type is WatchEventType.PUT:
                    watcher._dyn_seen[ev.key] = ev.value
                else:
                    watcher._dyn_seen.pop(ev.key, None)
                watcher._emit(ev)
            else:
                self._buffer_early(frame["wid"], frame)
        elif kind == "msg":
            sub = self._subs.get(frame["sid"])
            if sub is not None:
                sub._emit(
                    Message(
                        subject=frame["subject"],
                        payload=frame["payload"],
                        reply=frame.get("reply"),
                    )
                )
            else:
                self._buffer_early(frame["sid"], frame)

    def _buffer_early(self, rid: int, frame: dict) -> None:
        if rid in self._dead_ids:
            return  # push racing a cancellation — drop, don't accumulate
        if len(self._early_pushes) >= 256 and rid not in self._early_pushes:
            return  # cap distinct ids; genuinely-early windows are tiny
        buf = self._early_pushes.setdefault(rid, [])
        if len(buf) < 4096:
            buf.append(frame)

    def _drain_early(self, rid: int) -> None:
        for frame in self._early_pushes.pop(rid, []):
            self._handle_push(frame)

    async def _rpc_now(self, op: str, rpc_timeout: Optional[float] = 30.0, **kwargs) -> dict:
        """Issue an RPC on the current connection (no reconnect gate) —
        used by session restore, which runs while disconnected-for-users."""
        if self._writer is None:
            raise ConnectionError("client not connected")
        rid = next(self._ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        async with self._send_lock:
            write_frame(self._writer, {"op": op, "id": rid, **kwargs})
            await self._writer.drain()
        resp = await asyncio.wait_for(fut, rpc_timeout)
        if not resp.get("ok"):
            raise RuntimeError(f"dynstore {op} failed: {resp.get('error')}")
        return resp

    async def _rpc(self, op: str, rpc_timeout: Optional[float] = 30.0, **kwargs) -> dict:
        if not self._connected.is_set() and self.reconnect and not self._closed:
            try:
                await asyncio.wait_for(
                    self._connected.wait(), self.max_reconnect_wait
                )
            except asyncio.TimeoutError:
                raise ConnectionError(
                    f"dynstore unreachable for {self.max_reconnect_wait}s"
                ) from None
        return await self._rpc_now(op, rpc_timeout, **kwargs)

    # --- DiscoveryClient ---

    def _server_lease(self, lease_id: Optional[int]) -> Optional[int]:
        """Client lease handle → current server lease id. Handles are
        stable across reconnects (endpoint keys embed them); the server id
        changes every re-grant."""
        if lease_id is None:
            return None
        state = self._client_leases.get(lease_id)
        return state["server"] if state else lease_id

    async def grant_lease(self, ttl: float = 10.0) -> Lease:
        resp = await self._rpc("lease_grant", ttl=ttl)
        handle = next(self._ids)
        self._client_leases[handle] = {
            "server": resp["lease"], "ttl": resp["ttl"], "keys": {},
        }
        lease = Lease(id=handle, ttl=resp["ttl"])
        self._keepalive_tasks[handle] = asyncio.create_task(self._keepalive(lease))
        return lease

    async def _keepalive(self, lease: Lease) -> None:
        while not self._closed and lease.id in self._client_leases:
            await asyncio.sleep(lease.ttl / 3.0)
            if not self._connected.is_set():
                # reconnect in progress; restore re-grants the lease
                await self._connected.wait()
                continue
            try:
                resp = await self._rpc_now(
                    "lease_keepalive", lease=self._server_lease(lease.id)
                )
                if not resp.get("alive"):
                    # the broker reaped the lease while the connection
                    # stayed up (e.g. a >ttl event-loop stall): re-grant it
                    # and re-put its keys right here — the reconnect path
                    # only covers connection loss
                    logger.warning(
                        "lease %d reaped while connected — re-granting", lease.id
                    )
                    state = self._client_leases.get(lease.id)
                    if state is not None:
                        g = await self._rpc_now("lease_grant", ttl=state["ttl"])
                        state["server"] = g["lease"]
                        for key, value in state["keys"].items():
                            await self._rpc_now(
                                "kv_put", key=key, value=value,
                                lease=state["server"],
                            )
            except (ConnectionError, RuntimeError, asyncio.TimeoutError):
                continue  # the read loop handles the disconnect

    async def revoke_lease(self, lease_id: int) -> None:
        task = self._keepalive_tasks.pop(lease_id, None)
        if task:
            task.cancel()
        state = self._client_leases.pop(lease_id, None)
        await self._rpc(
            "lease_revoke", lease=state["server"] if state else lease_id
        )

    def _track_lease_key(self, key: str, value: bytes, lease_id: Optional[int]) -> None:
        if lease_id is not None and lease_id in self._client_leases:
            self._client_leases[lease_id]["keys"][key] = value

    async def kv_create(self, key: str, value: bytes, lease_id: Optional[int] = None) -> bool:
        resp = await self._rpc(
            "kv_create", key=key, value=value, lease=self._server_lease(lease_id)
        )
        if resp["created"]:
            self._track_lease_key(key, value, lease_id)
        return resp["created"]

    async def kv_put(self, key: str, value: bytes, lease_id: Optional[int] = None) -> None:
        await self._rpc(
            "kv_put", key=key, value=value, lease=self._server_lease(lease_id)
        )
        self._track_lease_key(key, value, lease_id)

    async def kv_get(self, key: str) -> Optional[bytes]:
        return (await self._rpc("kv_get", key=key))["value"]

    async def kv_get_prefix(self, prefix: str) -> Dict[str, bytes]:
        return (await self._rpc("kv_get_prefix", prefix=prefix))["kvs"]

    async def kv_delete(self, key: str) -> None:
        await self._rpc("kv_delete", key=key)
        for state in self._client_leases.values():
            state["keys"].pop(key, None)

    async def watch_prefix(self, prefix: str):
        resp = await self._rpc("watch", prefix=prefix)
        wid = resp["wid"]

        def on_cancel():
            live_wid = watcher._dyn_wid  # may have been re-armed since
            self._watchers.pop(live_wid, None)
            self._early_pushes.pop(live_wid, None)
            self._dead_ids.add(live_wid)
            if not self._closed:
                self._spawn_bg(self._rpc("unwatch", wid=live_wid))

        watcher = PrefixWatcher(on_cancel=on_cancel)
        watcher._dyn_prefix = prefix
        watcher._dyn_wid = wid
        watcher._dyn_seen = dict(resp["kvs"])
        self._watchers[wid] = watcher
        self._drain_early(wid)
        return resp["kvs"], watcher

    # --- MessagingClient ---

    async def publish(self, subject: str, payload: bytes) -> None:
        await self._rpc("pub", subject=subject, payload=payload)

    def _make_sub(self, sid: int, subject: str, group: Optional[str]) -> Subscription:
        def on_cancel():
            live_sid = sub._dyn_sid  # may have been re-armed since
            self._subs.pop(live_sid, None)
            self._early_pushes.pop(live_sid, None)
            self._dead_ids.add(live_sid)
            if not self._closed:
                self._spawn_bg(self._rpc("unsub", sid=live_sid))

        sub = Subscription(on_cancel=on_cancel)
        sub._dyn_subject = subject
        sub._dyn_group = group
        sub._dyn_sid = sid
        self._subs[sid] = sub
        self._drain_early(sid)
        return sub

    async def subscribe(self, subject: str) -> Subscription:
        resp = await self._rpc("sub", subject=subject)
        return self._make_sub(resp["sid"], subject, None)

    async def service_subscribe(self, subject: str, queue_group: str) -> Subscription:
        resp = await self._rpc("sub", subject=subject, group=queue_group)
        return self._make_sub(resp["sid"], subject, queue_group)

    async def request(self, subject: str, payload: bytes, timeout: float = 30.0) -> bytes:
        reply_subject = f"_inbox.{id(self)}.{next(self._ids)}"
        reply_sub = await self.subscribe(reply_subject)
        try:
            resp = await self._rpc("pub", subject=subject, payload=payload, reply=reply_subject)
            if resp.get("delivered", 0) == 0:
                raise ConnectionError(f"no responders on subject {subject!r}")
            msg = await asyncio.wait_for(reply_sub.__anext__(), timeout)
            return msg.payload
        finally:
            reply_sub.cancel()

    async def queue_push(self, queue: str, payload: bytes) -> None:
        await self._rpc("queue_push", queue=queue, payload=payload)

    async def queue_pop(
        self, queue: str, timeout: Optional[float] = None, visibility: float = 60.0
    ) -> Optional[WorkItem]:
        while True:
            try:
                resp = await self._rpc(
                    "queue_pop",
                    rpc_timeout=None if timeout is None else timeout + 5.0,
                    queue=queue,
                    **({"timeout": timeout} if timeout is not None else {}),
                    visibility=visibility,
                )
                break
            except ConnectionError:
                # an indefinitely-blocking pop rides out broker restarts;
                # timed pops surface the error (callers own the retry)
                if timeout is not None or self._closed or not self.reconnect:
                    raise
        if resp["payload"] is None:
            return None
        item_id = resp["item"]

        def ack():
            self._spawn_bg(self._rpc("queue_ack", queue=queue, item=item_id))

        return WorkItem(payload=resp["payload"], ack=ack)

    async def queue_depth(self, queue: str) -> int:
        return (await self._rpc("queue_depth", queue=queue))["depth"]


def main() -> None:
    parser = argparse.ArgumentParser(description="dynamo-tpu control/message plane server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()
    from ...utils.logging import setup_logging
    setup_logging(logging.DEBUG if args.verbose else logging.INFO)
    server = DynStoreServer(args.host, args.port)
    asyncio.run(server.serve_forever())


if __name__ == "__main__":
    main()
