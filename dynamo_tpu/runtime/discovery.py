"""Discovery plane: lease-scoped KV store with prefix watch.

This is the control plane of the framework — the role etcd plays in the
reference (reference: lib/runtime/src/transports/etcd.rs:40-520 — kv_create
txn semantics, prefix watch with Put/Delete events, auto-renewed primary
lease whose loss is the liveness signal). Two implementations exist:
in-memory (tests, single-process serving) and the dynstore TCP server
(multi-process / multi-host).

Liveness contract: every serving endpoint registers its key under the
worker's *primary lease*. If the worker dies, keep-alives stop, the lease
expires, the server deletes the key, and every watcher sees a Delete event —
routers stop routing there with zero extra coordination.
"""

from __future__ import annotations

import abc
import asyncio
import dataclasses
import enum
from typing import AsyncIterator, Dict, List, Optional, Tuple


class WatchEventType(enum.Enum):
    PUT = "put"
    DELETE = "delete"


@dataclasses.dataclass
class WatchEvent:
    type: WatchEventType
    key: str
    value: bytes


@dataclasses.dataclass
class Lease:
    id: int
    ttl: float


class DiscoveryClient(abc.ABC):
    """Lease + KV + watch surface shared by all discovery transports."""

    @abc.abstractmethod
    async def grant_lease(self, ttl: float = 10.0) -> Lease:
        """Create a lease; the client auto-keeps-it-alive until revoked."""

    @abc.abstractmethod
    async def revoke_lease(self, lease_id: int) -> None:
        """Revoke: all keys attached to the lease are deleted server-side."""

    @abc.abstractmethod
    async def kv_create(self, key: str, value: bytes, lease_id: Optional[int] = None) -> bool:
        """Transactional create — returns False if the key already exists."""

    @abc.abstractmethod
    async def kv_put(self, key: str, value: bytes, lease_id: Optional[int] = None) -> None:
        """Unconditional upsert."""

    @abc.abstractmethod
    async def kv_get(self, key: str) -> Optional[bytes]:
        pass

    @abc.abstractmethod
    async def kv_get_prefix(self, prefix: str) -> Dict[str, bytes]:
        pass

    @abc.abstractmethod
    async def kv_delete(self, key: str) -> None:
        pass

    @abc.abstractmethod
    async def watch_prefix(
        self, prefix: str
    ) -> Tuple[Dict[str, bytes], "PrefixWatcher"]:
        """Current snapshot + a watcher yielding subsequent events."""

    async def primary_lease(self) -> Lease:
        """The client's default lease, created lazily, shared by all endpoints."""
        if getattr(self, "_primary_lease", None) is None:
            self._primary_lease = await self.grant_lease()
        return self._primary_lease

    async def close(self) -> None:
        pass


class PrefixWatcher:
    """Async stream of WatchEvents for one prefix; cancel() to stop.

    ``on_cancel`` lets the owning transport release server-side watch state.
    """

    def __init__(self, on_cancel=None) -> None:
        self._queue: asyncio.Queue = asyncio.Queue()
        self._cancelled = False
        self._on_cancel = on_cancel

    def _emit(self, event: WatchEvent) -> None:
        if not self._cancelled:
            self._queue.put_nowait(event)

    def cancel(self) -> None:
        if self._cancelled:
            return
        self._cancelled = True
        self._queue.put_nowait(None)
        if self._on_cancel is not None:
            self._on_cancel()

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self._queue.get()
        if ev is None:
            raise StopAsyncIteration
        return ev


async def kv_create_or_validate(
    client: DiscoveryClient, key: str, value: bytes, lease_id: Optional[int] = None
) -> bool:
    """Create, or succeed iff the existing value matches (config agreement)."""
    if await client.kv_create(key, value, lease_id):
        return True
    existing = await client.kv_get(key)
    return existing == value
