"""Network data plane: dial-back response streaming.

Request/response flow across processes (mirrors the reference's two-part
message + TCP dial-back design, reference:
lib/runtime/src/pipeline/network/egress/push.rs:88-180 and
network/tcp/{server,client}.rs):

1. The *requester* registers a stream with its process-wide ``StreamServer``
   and gets a ``conn_info`` descriptor (scheme/host/port/stream_id).
2. The request — a two-part message ``{header: {req_id, conn}, payload}`` —
   is pushed over the message plane to the chosen worker instance subject.
3. The *worker* dials back (TCP, or a process-local queue when both ends
   share a process), sends a prologue (``ok`` or an engine-creation error),
   then streams data frames; ``stop``/``kill`` control frames flow
   requester→worker on the same connection.

Frames are 4-byte length-prefixed msgpack maps:
  worker→requester: {t: "prologue", ok, error?} | {t: "data", payload} | {t: "end"}
  requester→worker: {t: "stop"} | {t: "kill"}
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Any, AsyncIterator, Callable, Dict, Optional, Tuple

import msgpack

from .engine import AsyncEngineContext, Context, EngineError
from .transports.dynstore import read_frame, write_frame

logger = logging.getLogger(__name__)


class ResponseStreamError(Exception):
    """The worker reported an error in the stream prologue or mid-stream."""


class _LocalStream:
    """In-process dial-back: a pair of queues instead of a socket."""

    def __init__(self) -> None:
        self.to_requester: asyncio.Queue = asyncio.Queue()
        self.to_worker: asyncio.Queue = asyncio.Queue()


_local_streams: Dict[str, _LocalStream] = {}


class StreamServer:
    """Per-process receiver for dial-back response streams.

    Lazily started TCP listener (reference: DistributedRuntime::tcp_server,
    lib/runtime/src/distributed.rs:135). Also owns the process-local stream
    registry used when requester and worker share a process.
    """

    def __init__(self, host: str = "127.0.0.1", advertise_host: Optional[str] = None):
        self.host = host
        self.advertise_host = advertise_host or host
        self.port: Optional[int] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._ids = itertools.count(1)
        self._waiting: Dict[str, asyncio.Future] = {}
        self._start_lock: Optional[asyncio.Lock] = None

    async def ensure_started(self) -> None:
        if self._server is not None:
            return
        if self._start_lock is None:
            self._start_lock = asyncio.Lock()
        async with self._start_lock:
            if self._server is not None:
                return
            server = await asyncio.start_server(self._accept, self.host, 0)
            self.port = server.sockets[0].getsockname()[1]
            self._server = server

    async def _accept(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        handshake = await read_frame(reader)
        if handshake is None:
            writer.close()
            return
        stream_id = handshake.get("stream")
        fut = self._waiting.pop(stream_id, None)
        if fut is None or fut.done():
            logger.warning("dial-back for unknown stream %s", stream_id)
            writer.close()
            return
        fut.set_result((reader, writer))

    async def register_tcp(self) -> Tuple[dict, asyncio.Future]:
        """Returns (conn_info, future resolving to (reader, writer))."""
        await self.ensure_started()
        stream_id = f"s{next(self._ids)}"
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiting[stream_id] = fut
        conn = {"scheme": "tcp", "host": self.advertise_host, "port": self.port, "stream": stream_id}
        return conn, fut

    def register_local(self) -> Tuple[dict, _LocalStream]:
        stream_id = f"l{next(self._ids)}"
        stream = _LocalStream()
        _local_streams[stream_id] = stream
        conn = {"scheme": "local", "stream": stream_id}
        return conn, stream

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None


async def respond_to(
    conn_info: dict,
    stream_fn: Callable[[AsyncEngineContext], AsyncIterator[Any]],
    request_id: str,
    trace_id: Optional[str] = None,
    span_source: str = "worker",
) -> None:
    """Worker side: dial back and pump ``stream_fn``'s output to the requester.

    Control frames from the requester (stop/kill) are applied to the
    engine context while streaming. ``trace_id`` is the ingress-assigned
    correlation id riding the message header; ``request_id`` (the per-hop
    wire id) keys worker-side engine state. ``span_source`` names this
    process in the cluster-stitched trace: the ``end`` frame piggybacks
    the context's span marks (plus any remote sets it collected from
    planes further downstream) back to the requester, stamped with the
    request-receipt and response-send wall times the requester needs for
    clock-offset estimation (telemetry/stitch.py).
    """
    import time as _time

    recv_at = _time.time()  # request receipt on THIS process's clock
    ctx = AsyncEngineContext(request_id, trace_id=trace_id)
    scheme = conn_info.get("scheme")
    if scheme == "local":
        stream = _local_streams.pop(conn_info["stream"], None)
        if stream is None:
            logger.warning("local stream %s vanished", conn_info.get("stream"))
            return

        async def send(frame: dict) -> None:
            stream.to_requester.put_nowait(frame)

        async def control_loop():
            while True:
                frame = await stream.to_worker.get()
                if frame is None:
                    return
                _apply_control(frame, ctx)

        ctrl_task = asyncio.create_task(control_loop())
        try:
            await _pump(stream_fn, ctx, send, span_source, recv_at)
        finally:
            ctrl_task.cancel()
        return

    if scheme == "tcp":
        try:
            reader, writer = await asyncio.open_connection(conn_info["host"], conn_info["port"])
        except OSError as e:
            logger.warning("dial-back to %s failed: %s", conn_info, e)
            return
        write_frame(writer, {"stream": conn_info["stream"]})
        await writer.drain()

        async def control_loop():
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    # requester went away entirely → kill
                    ctx.kill()
                    return
                _apply_control(frame, ctx)

        ctrl_task = asyncio.create_task(control_loop())

        async def send(frame: dict) -> None:
            # drain per frame: backpressure from a slow requester propagates
            # into the generator instead of ballooning the send buffer
            write_frame(writer, frame)
            await writer.drain()

        try:
            await _pump(stream_fn, ctx, send, span_source, recv_at)
        except (ConnectionResetError, BrokenPipeError):
            ctx.kill()
        finally:
            ctrl_task.cancel()
            writer.close()
        return

    raise ValueError(f"unknown conn scheme {scheme!r}")


def _apply_control(frame: dict, ctx: AsyncEngineContext) -> None:
    t = frame.get("t")
    if t == "stop":
        ctx.stop_generating()
    elif t == "kill":
        ctx.kill()


async def _pump(
    stream_fn: Callable[[AsyncEngineContext], AsyncIterator[Any]],
    ctx: AsyncEngineContext,
    send,
    span_source: str = "worker",
    recv_at: float = 0.0,
) -> None:
    # Prime the first item BEFORE the prologue: async generators don't run
    # their body until first iteration, so engine-creation errors (EngineError)
    # only surface here — this is what makes the error-prologue contract real.
    try:
        stream = stream_fn(ctx).__aiter__()
        first: Any = await stream.__anext__()
        have_first = True
    except EngineError as e:
        await send({"t": "prologue", "ok": False, "error": str(e)})
        return
    except StopAsyncIteration:
        have_first = False
    except Exception as e:
        logger.exception("engine failed before first response %s", ctx.id)
        await send({"t": "prologue", "ok": False, "error": f"{type(e).__name__}: {e}"})
        return
    await send({"t": "prologue", "ok": True})
    try:
        if have_first and not ctx.is_killed:
            await send({"t": "data", "payload": first})
            async for item in stream:
                if ctx.is_killed:
                    break
                await send({"t": "data", "payload": item})
        # span export piggybacks on the end frame (no extra round trip):
        # this process's marks plus every remote set IT collected from
        # planes further downstream (remote prefill commit, a nested
        # worker hop) — the requester folds them with an offset estimate
        # from (its send time, recv_at, resp_sent_at, its receive time)
        end: dict = {"t": "end"}
        if ctx.stages or ctx.remote_spans:
            import time as _time

            end.update({
                "source": span_source,
                "spans": ctx.export_spans(),
                "children": list(ctx.remote_spans),
                "recv_at": recv_at,
                "resp_sent_at": _time.time(),
            })
        await send(end)
    except Exception as e:  # stream died mid-flight: tell the requester
        logger.exception("response stream %s failed", ctx.id)
        await send({"t": "err", "error": f"{type(e).__name__}: {e}"})


class ResponseReceiver:
    """Requester side: consumes the dialed-back stream as an async iterator."""

    def __init__(self, context: AsyncEngineContext):
        self.context = context
        self._queue: asyncio.Queue = asyncio.Queue()
        self._send_control: Optional[Callable[[dict], None]] = None
        # span export off the end frame: the worker's marks + the wall
        # times the offset estimate needs; resp_recv_at is stamped HERE
        # (this process's clock) when the end frame lands
        self.remote_spans: Optional[dict] = None
        self.resp_recv_at: float = 0.0
        self._prologue: asyncio.Future = asyncio.get_event_loop().create_future()
        # strong ref to the frame-pump task; bare create_task results can be
        # garbage-collected mid-stream, silently freezing the receiver
        self._pump_task: Optional[asyncio.Task] = None

    def stop_generating(self) -> None:
        self.context.stop_generating()
        if self._send_control:
            self._send_control({"t": "stop"})

    def kill(self) -> None:
        self.context.kill()
        if self._send_control:
            self._send_control({"t": "kill"})

    async def wait_prologue(self, timeout: float = 600.0) -> None:
        # generous default: the prologue follows the FIRST response item, so
        # it legitimately waits through cold-start XLA compilation
        """Raises ResponseStreamError if the worker rejected the request."""
        await asyncio.wait_for(asyncio.shield(self._prologue), timeout)
        err = self._prologue.result()
        if err is not None:
            raise ResponseStreamError(err)

    def _feed(self, frame: Optional[dict]) -> bool:
        """Returns False when the stream is finished."""
        if frame is None:
            if not self._prologue.done():
                self._prologue.set_result("connection lost before prologue")
            self._queue.put_nowait(("err", "connection lost"))
            return False
        t = frame.get("t")
        if t == "prologue":
            if not self._prologue.done():
                self._prologue.set_result(None if frame.get("ok") else frame.get("error", "engine error"))
            return True
        if t == "data":
            self._queue.put_nowait(("data", frame["payload"]))
            return True
        if t == "end":
            if frame.get("spans") or frame.get("children"):
                import time as _time

                self.remote_spans = frame
                self.resp_recv_at = _time.time()
            self._queue.put_nowait(("end", None))
            return False
        if t == "err":
            self._queue.put_nowait(("err", frame.get("error", "stream error")))
            return False
        return True

    def __aiter__(self):
        return self

    async def __anext__(self):
        kind, value = await self._queue.get()
        if kind == "data":
            return value
        if kind == "end":
            raise StopAsyncIteration
        raise ResponseStreamError(value)


async def open_response_stream(
    stream_server: StreamServer, local: bool
) -> Tuple[dict, ResponseReceiver]:
    """Requester side setup. Returns (conn_info to embed in the request,
    receiver to iterate)."""
    ctx = AsyncEngineContext()
    receiver = ResponseReceiver(ctx)

    if local:
        conn, stream = stream_server.register_local()

        def send_control(frame: dict) -> None:
            stream.to_worker.put_nowait(frame)

        receiver._send_control = send_control

        async def pump_local():
            while True:
                frame = await stream.to_requester.get()
                if not receiver._feed(frame):
                    break

        receiver._pump_task = asyncio.create_task(pump_local())
        return conn, receiver

    conn, fut = await stream_server.register_tcp()

    async def pump_tcp():
        try:
            reader, writer = await asyncio.wait_for(fut, 60.0)
        except asyncio.TimeoutError:
            stream_server._waiting.pop(conn["stream"], None)
            receiver._feed(None)
            return

        def send_control(frame: dict) -> None:
            try:
                write_frame(writer, frame)
            except (ConnectionResetError, RuntimeError):
                pass

        receiver._send_control = send_control
        try:
            while True:
                frame = await read_frame(reader)
                if not receiver._feed(frame):
                    break
        finally:
            writer.close()

    receiver._pump_task = asyncio.create_task(pump_tcp())
    return conn, receiver
