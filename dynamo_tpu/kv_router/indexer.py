"""Global prefix index: which worker holds which KV blocks.

A radix/trie over *chained block hashes*: each node is one cached block
(identified by its sequence hash — i.e. the whole prefix ending there),
holding the set of workers that advertise it. ``find_matches`` walks a
request's block-hash chain from the root and scores workers by how many
consecutive blocks they already hold.

Reference analog: lib/llm/src/kv_router/indexer.rs — RadixTree with a
lookup map keyed by block hash, early-exit scoring, apply_event
Stored/Removed, remove_worker, and a sharded variant. The single-threaded
actor there becomes a plain asyncio-confined object here (one event loop ==
one thread); ``ShardedKvIndexer`` partitions workers for scale.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Set

from .protocols import RouterEvent


@dataclasses.dataclass
class OverlapScores:
    """worker → number of consecutive prefix blocks already cached."""

    scores: Dict[str, int] = dataclasses.field(default_factory=dict)
    # block hash → how many workers hold it (frequency info for policies)
    frequencies: List[int] = dataclasses.field(default_factory=list)
    # worker → ADDITIONAL consecutive blocks past its warm run that the
    # worker can rehydrate from its cold tier (kv/cold_tier.py spill
    # advertisements, RouterEvent tier="cold"); scored discounted vs a
    # warm hit by KvScheduler.cold_discount
    cold_scores: Dict[str, int] = dataclasses.field(default_factory=dict)

    def merge(self, other: "OverlapScores") -> None:
        for w, s in other.scores.items():
            self.scores[w] = max(self.scores.get(w, 0), s)
        for w, s in other.cold_scores.items():
            self.cold_scores[w] = max(self.cold_scores.get(w, 0), s)
        # frequencies are per-depth holder counts — sum element-wise
        if len(other.frequencies) > len(self.frequencies):
            self.frequencies.extend([0] * (len(other.frequencies) - len(self.frequencies)))
        for i, f in enumerate(other.frequencies):
            self.frequencies[i] += f


class _Node:
    __slots__ = ("hash", "parent", "children", "workers", "last_update")

    def __init__(self, h: Optional[int], parent: Optional["_Node"]):
        self.hash = h
        self.parent = parent
        self.children: Dict[int, _Node] = {}
        self.workers: Set[str] = set()
        self.last_update = time.monotonic()


class RadixTree:
    def __init__(self, expiration_s: Optional[float] = None):
        self.root = _Node(None, None)
        self.lookup: Dict[int, _Node] = {}
        self.expiration_s = expiration_s

    def find_matches(
        self, block_hashes: List[int], early_exit: bool = False
    ) -> OverlapScores:
        """Walk the chain from the root; score consecutive holders."""
        out = OverlapScores()
        node = self.root
        now = time.monotonic()
        active: Optional[Set[str]] = None  # workers still matching consecutively
        for h in block_hashes:
            child = node.children.get(h)
            if child is None:
                break
            if self.expiration_s is not None and now - child.last_update > self.expiration_s:
                break
            holders = child.workers
            active = holders if active is None else (active & holders)
            if not active:
                break
            for w in active:
                out.scores[w] = out.scores.get(w, 0) + 1
            out.frequencies.append(len(holders))
            if early_exit and len(active) == 1:
                # single candidate — extend its score cheaply down the chain
                (only,) = active
                n = child
                for h2 in block_hashes[len(out.frequencies):]:
                    n = n.children.get(h2)
                    if n is None or only not in n.workers:
                        break
                    out.scores[only] += 1
                    out.frequencies.append(len(n.workers))
                break
            node = child
        return out

    def apply_event(self, event: RouterEvent) -> None:
        if event.stored is not None:
            parent = (
                self.lookup.get(event.stored.parent_hash)
                if event.stored.parent_hash is not None
                else self.root
            )
            if parent is None:
                # parent unknown (dropped/expired) — root the chain here so the
                # blocks are still discoverable standalone
                parent = self.root
            for h in event.stored.block_hashes:
                node = self.lookup.get(h)
                if node is None:
                    node = _Node(h, parent)
                    parent.children[h] = node
                    self.lookup[h] = node
                elif node.parent is self.root and parent is not self.root:
                    # node was orphan-rooted (its parent event arrived late or
                    # was dropped) — re-link under its real parent so prefix
                    # walks see the full chain
                    self.root.children.pop(h, None)
                    node.parent = parent
                    parent.children[h] = node
                node.workers.add(event.worker_id)
                node.last_update = time.monotonic()
                parent = node
        if event.removed is not None:
            for h in event.removed.block_hashes:
                node = self.lookup.get(h)
                if node is None:
                    continue
                node.workers.discard(event.worker_id)
                if not node.workers and not node.children:
                    self._prune(node)

    def _prune(self, node: "_Node") -> None:
        while node is not None and node is not self.root:
            if node.workers or node.children:
                break
            parent = node.parent
            if parent is not None:
                parent.children.pop(node.hash, None)
            self.lookup.pop(node.hash, None)
            node = parent

    def remove_worker(self, worker_id: str) -> None:
        dead = []
        for h, node in self.lookup.items():
            node.workers.discard(worker_id)
            if not node.workers and not node.children:
                dead.append(node)
        for node in dead:
            self._prune(node)

    def clear_expired(self) -> int:
        if self.expiration_s is None:
            return 0
        cutoff = time.monotonic() - self.expiration_s
        dead = [n for n in self.lookup.values() if n.last_update < cutoff and not n.children]
        for n in dead:
            self._prune(n)
        return len(dead)

    def __len__(self) -> int:
        return len(self.lookup)


class _NativeTreeAdapter:
    """Presents the RadixTree surface over the C++ tree (dynamo_tpu/native).

    The native tree is the production path — prefix matching is on every
    scheduling decision (reference runs it on a dedicated Rust actor thread,
    indexer.rs:499-663); the Python RadixTree above is the always-available
    fallback and the executable spec the native side is tested against.
    """

    def __init__(self, native_mod, expiration_s: Optional[float]):
        self._tree = native_mod.NativeRadixTree(expiration_s)

    def apply_event(self, event: RouterEvent) -> None:
        if event.stored is not None:
            self._tree.apply_stored(
                event.worker_id, event.stored.parent_hash, event.stored.block_hashes
            )
        if event.removed is not None:
            self._tree.apply_removed(event.worker_id, event.removed.block_hashes)

    def find_matches(
        self, block_hashes: List[int], early_exit: bool = False
    ) -> OverlapScores:
        scores, freqs = self._tree.find_matches(block_hashes, early_exit)
        return OverlapScores(scores=scores, frequencies=freqs)

    def remove_worker(self, worker_id: str) -> None:
        self._tree.remove_worker(worker_id)

    def clear_expired(self) -> int:
        return self._tree.clear_expired()

    def __len__(self) -> int:
        return len(self._tree)


def _make_tree(expiration_s: Optional[float], use_native: Optional[bool]):
    try:
        from .. import native
    except Exception as e:
        # pure-Python fallback is the design, but WHY the native core
        # failed to import must be discoverable, not silent
        logging.getLogger(__name__).debug("native core unavailable: %s", e)
        native = None
    if use_native is None and native is not None and native.disabled_by_env():
        use_native = False  # operator kill-switch (explicit True overrides)
    if use_native is False:
        return RadixTree(expiration_s)
    if native is not None and native.available():
        return _NativeTreeAdapter(native, expiration_s)
    if use_native:
        raise RuntimeError("native indexer requested but C++ core unavailable")
    return RadixTree(expiration_s)


class KvIndexer:
    """Event-consuming index (the actor surface of the reference).

    ``use_native``: None (default) auto-selects the C++ tree when built,
    True requires it, False forces the pure-Python tree.
    """

    def __init__(
        self,
        block_size: int = 16,
        expiration_s: Optional[float] = None,
        use_native: Optional[bool] = None,
    ):
        self.block_size = block_size
        self.tree = _make_tree(expiration_s, use_native)
        self.events_applied = 0
        self.worker_ids: set = set()  # every worker ever seen in events
        # cold-tier ownership (RouterEvent tier="cold"), kept BESIDE the
        # warm tree (both tree implementations stay tier-blind): hash →
        # workers that can rehydrate the block from their cold tier
        self._cold: Dict[int, Set[str]] = {}

    def apply_event(self, event: RouterEvent) -> None:
        if getattr(event, "tier", "hbm") == "cold":
            self._apply_cold(event)
        else:
            self.tree.apply_event(event)
        self.worker_ids.add(event.worker_id)
        self.events_applied += 1

    def _apply_cold(self, event: RouterEvent) -> None:
        wid = event.worker_id
        if event.stored is not None:
            for h in event.stored.block_hashes:
                self._cold.setdefault(h, set()).add(wid)
        if event.removed is not None:
            for h in event.removed.block_hashes:
                holders = self._cold.get(h)
                if holders is not None:
                    holders.discard(wid)
                    if not holders:
                        del self._cold[h]

    def find_matches(self, block_hashes: List[int]) -> OverlapScores:
        out = self.tree.find_matches(block_hashes)
        if self._cold:
            self._extend_cold(out, block_hashes)
        return out

    def _extend_cold(self, out: OverlapScores,
                     block_hashes: List[int]) -> None:
        """Per-worker cold extension: how many consecutive blocks PAST a
        worker's warm run it can still rehydrate from cold spill files.
        Cold blocks also bridge from position 0 for workers with no warm
        hit at all (the respawned-worker case)."""
        candidates: Set[str] = set(out.scores)
        for h in block_hashes:
            holders = self._cold.get(h)
            if holders:
                candidates.update(holders)
        for w in candidates:
            warm = out.scores.get(w, 0)
            i = warm
            while i < len(block_hashes) and w in self._cold.get(
                    block_hashes[i], ()):
                i += 1
            if i > warm:
                out.cold_scores[w] = i - warm

    def find_matches_for_request(self, token_ids: List[int]) -> OverlapScores:
        from ..tokens import compute_block_hashes

        return self.find_matches(compute_block_hashes(token_ids, self.block_size))

    def remove_worker(self, worker_id: str) -> None:
        self.tree.remove_worker(worker_id)
        self.worker_ids.discard(worker_id)
        for h in list(self._cold):
            holders = self._cold[h]
            holders.discard(worker_id)
            if not holders:
                del self._cold[h]


class ShardedKvIndexer:
    """Workers partitioned across N independent trees (reference:
    indexer.rs KvIndexerSharded). Queries fan out and merge."""

    def __init__(self, num_shards: int, block_size: int = 16):
        self.block_size = block_size
        self.shards = [KvIndexer(block_size) for _ in range(num_shards)]
        self._assignment: Dict[str, int] = {}

    def _shard_for(self, worker_id: str) -> KvIndexer:
        idx = self._assignment.get(worker_id)
        if idx is None:
            # least-loaded assignment
            loads = [len(s.tree) for s in self.shards]
            idx = loads.index(min(loads))
            self._assignment[worker_id] = idx
        return self.shards[idx]

    def apply_event(self, event: RouterEvent) -> None:
        self._shard_for(event.worker_id).apply_event(event)

    def find_matches(self, block_hashes: List[int]) -> OverlapScores:
        out = OverlapScores()
        for shard in self.shards:
            out.merge(shard.find_matches(block_hashes))
        return out

    def remove_worker(self, worker_id: str) -> None:
        idx = self._assignment.pop(worker_id, None)
        if idx is not None:
            self.shards[idx].remove_worker(worker_id)

    @property
    def worker_ids(self) -> set:
        return set(self._assignment)
