"""Router-side metrics collection: periodic stats scrape of all instances.

Reference analog: lib/llm/src/kv_router/metrics_aggregator.rs — 100ms poll
loop with a short scrape timeout feeding a ProcessedEndpoints snapshot.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..runtime.client import Client
from .protocols import ForwardPassMetrics

logger = logging.getLogger(__name__)


class KvMetricsAggregator:
    def __init__(
        self,
        client: Client,
        poll_interval: float = 0.1,
        scrape_timeout: float = 0.3,
        on_update: Optional[Callable[[str, ForwardPassMetrics], None]] = None,
        on_remove: Optional[Callable[[str], None]] = None,
        on_sync: Optional[Callable[[set], None]] = None,
    ):
        self.client = client
        self.poll_interval = poll_interval
        self.scrape_timeout = scrape_timeout
        self.on_update = on_update
        self.on_remove = on_remove
        self.on_sync = on_sync
        self.endpoints: Dict[str, ForwardPassMetrics] = {}
        # monotonic time of each worker's last successful scrape — the
        # staleness age tells operators (and the scheduler cost function's
        # observers) how old a worker's load snapshot is
        self.last_update: Dict[str, float] = {}
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = self.client.endpoint.drt.runtime.spawn(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                await self.poll_once()
            except Exception:
                logger.exception("metrics poll failed")
            await asyncio.sleep(self.poll_interval)

    async def poll_once(self) -> Dict[str, ForwardPassMetrics]:
        stats = await self.client.scrape_stats(timeout=self.scrape_timeout)
        seen = set()
        for iid, s in stats.items():
            data = s.get("data")
            if data is None:
                continue
            m = ForwardPassMetrics.from_wire(data)
            self.endpoints[iid] = m
            self.last_update[iid] = time.monotonic()
            seen.add(iid)
            if self.on_update:
                self.on_update(iid, m)
        # drop workers that vanished from discovery
        live = set(self.client.instance_ids())
        for iid in list(self.endpoints):
            if iid not in live:
                del self.endpoints[iid]
                self.last_update.pop(iid, None)
                if self.on_remove:
                    self.on_remove(iid)
        if self.on_sync:
            # lets the owner purge state for workers that never produced a
            # successful scrape (e.g. died before their first poll)
            self.on_sync(live)
        return self.endpoints

    def register_into(self, registry, prefix: str = "dynamo") -> None:
        """Expose the per-worker snapshot as labelled gauges on a
        MetricsRegistry (the router-side /metrics surface)."""

        def per_worker(field: str) -> Callable[[], List[Tuple[dict, float]]]:
            # renders off-loop while the poll loop inserts/expires
            # workers — iterate a snapshot, or a scrape racing a sync
            # raises "dictionary changed size during iteration" and the
            # gauge silently vanishes from /metrics
            # dynrace: domain(executor)
            def collect():
                return [
                    ({"instance": iid}, float(getattr(m, field)))
                    for iid, m in list(self.endpoints.items())
                ]
            return collect

        registry.callback_gauge(
            f"{prefix}_kv_router_worker_kv_active_blocks",
            "Worker's in-use KV blocks (scraped ForwardPassMetrics)",
            per_worker("kv_active_blocks"),
        )
        registry.callback_gauge(
            f"{prefix}_kv_router_worker_kv_total_blocks",
            "Worker's KV capacity in blocks (scraped)",
            per_worker("kv_total_blocks"),
        )
        registry.callback_gauge(
            f"{prefix}_kv_router_worker_active_slots",
            "Worker's busy batch slots (scraped)",
            per_worker("request_active_slots"),
        )
        registry.callback_gauge(
            f"{prefix}_kv_router_worker_waiting_requests",
            "Worker's admission-queue depth (scraped)",
            per_worker("num_requests_waiting"),
        )
        registry.callback_gauge(
            f"{prefix}_kv_router_worker_prefix_hit_ratio",
            "Worker's prefix-cache hit rate (scraped)",
            per_worker("gpu_prefix_cache_hit_rate"),
        )
        registry.callback_gauge(
            f"{prefix}_kv_router_worker_staleness_seconds",
            "Age of the worker's last successful stats scrape",
            # dynrace: domain(executor)
            lambda: [
                ({"instance": iid}, time.monotonic() - t)
                for iid, t in list(self.last_update.items())
            ],
        )

    def stop(self) -> None:
        if self._task:
            self._task.cancel()
