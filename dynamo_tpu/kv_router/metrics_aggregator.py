"""Router-side metrics collection: periodic stats scrape of all instances.

Reference analog: lib/llm/src/kv_router/metrics_aggregator.rs — 100ms poll
loop with a short scrape timeout feeding a ProcessedEndpoints snapshot.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Dict, Optional

from ..runtime.client import Client
from .protocols import ForwardPassMetrics

logger = logging.getLogger(__name__)


class KvMetricsAggregator:
    def __init__(
        self,
        client: Client,
        poll_interval: float = 0.1,
        scrape_timeout: float = 0.3,
        on_update: Optional[Callable[[str, ForwardPassMetrics], None]] = None,
        on_remove: Optional[Callable[[str], None]] = None,
        on_sync: Optional[Callable[[set], None]] = None,
    ):
        self.client = client
        self.poll_interval = poll_interval
        self.scrape_timeout = scrape_timeout
        self.on_update = on_update
        self.on_remove = on_remove
        self.on_sync = on_sync
        self.endpoints: Dict[str, ForwardPassMetrics] = {}
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = self.client.endpoint.drt.runtime.spawn(self._loop())

    async def _loop(self) -> None:
        while True:
            try:
                await self.poll_once()
            except Exception:
                logger.exception("metrics poll failed")
            await asyncio.sleep(self.poll_interval)

    async def poll_once(self) -> Dict[str, ForwardPassMetrics]:
        stats = await self.client.scrape_stats(timeout=self.scrape_timeout)
        seen = set()
        for iid, s in stats.items():
            data = s.get("data")
            if data is None:
                continue
            m = ForwardPassMetrics.from_wire(data)
            self.endpoints[iid] = m
            seen.add(iid)
            if self.on_update:
                self.on_update(iid, m)
        # drop workers that vanished from discovery
        live = set(self.client.instance_ids())
        for iid in list(self.endpoints):
            if iid not in live:
                del self.endpoints[iid]
                if self.on_remove:
                    self.on_remove(iid)
        if self.on_sync:
            # lets the owner purge state for workers that never produced a
            # successful scrape (e.g. died before their first poll)
            self.on_sync(live)
        return self.endpoints

    def stop(self) -> None:
        if self._task:
            self._task.cancel()
