"""KV-router wire protocols.

Reference analog: lib/llm/src/kv_router/protocols.rs — RouterEvent,
KvCacheEvent Stored/Removed, ForwardPassMetrics.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class KvCacheStored:
    block_hashes: List[int]           # chained sequence hashes, in order
    parent_hash: Optional[int] = None  # sequence hash of the block before


@dataclasses.dataclass
class KvCacheRemoved:
    block_hashes: List[int]


@dataclasses.dataclass
class RouterEvent:
    worker_id: str
    stored: Optional[KvCacheStored] = None
    removed: Optional[KvCacheRemoved] = None
    event_id: int = 0
    # which tier holds the blocks: "hbm" (warm — the default, and the
    # only value before the KV fabric) or "cold" (content-addressed
    # spill files the worker can rehydrate; routers score it discounted
    # vs a warm hit — kv_router/scheduler.py cold_discount)
    tier: str = "hbm"

    def to_wire(self) -> dict:
        d: dict = {"worker_id": self.worker_id, "event_id": self.event_id}
        if self.tier != "hbm":
            d["tier"] = self.tier
        if self.stored is not None:
            d["stored"] = {
                "block_hashes": self.stored.block_hashes,
                "parent_hash": self.stored.parent_hash,
            }
        if self.removed is not None:
            d["removed"] = {"block_hashes": self.removed.block_hashes}
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "RouterEvent":
        stored = d.get("stored")
        removed = d.get("removed")
        return cls(
            worker_id=d["worker_id"],
            stored=KvCacheStored(
                block_hashes=list(stored["block_hashes"]),
                parent_hash=stored.get("parent_hash"),
            )
            if stored
            else None,
            removed=KvCacheRemoved(block_hashes=list(removed["block_hashes"]))
            if removed
            else None,
            event_id=d.get("event_id", 0),
            tier=d.get("tier", "hbm"),
        )


@dataclasses.dataclass
class ForwardPassMetrics:
    """Per-worker load snapshot (reference: kv_router/protocols.rs:42-54)."""

    request_active_slots: int = 0
    request_total_slots: int = 0
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    num_requests_waiting: int = 0
    gpu_cache_usage_perc: float = 0.0
    gpu_prefix_cache_hit_rate: float = 0.0
    # recovery drain (recovery/controller.py): a draining worker accepts
    # no new requests — routers must exclude it from every decision
    draining: bool = False

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, d: dict) -> "ForwardPassMetrics":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


KV_EVENT_SUBJECT = "kv_events"
KV_HIT_RATE_EVENT = "kv-hit-rate"
