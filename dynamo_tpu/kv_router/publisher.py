"""Worker-side KV plumbing: event publisher + metrics publisher.

Reference analog: lib/llm/src/kv_router/publisher.rs — KvEventPublisher
(engine block events → broker subject) and KvMetricsPublisher
(ForwardPassMetrics served via the endpoint stats handler).
"""

from __future__ import annotations

import asyncio
import itertools
import logging
from typing import Callable, List, Optional

from ..engine.block_allocator import KvEventSink
from ..runtime.component import Component
from .protocols import KV_EVENT_SUBJECT, ForwardPassMetrics, KvCacheRemoved, KvCacheStored, RouterEvent

logger = logging.getLogger(__name__)


class KvEventPublisher:
    """Queue-decoupled publisher: engine hooks are sync, broker IO is async."""

    def __init__(self, component: Component, worker_id: str):
        self.component = component
        self.worker_id = worker_id
        self._queue: asyncio.Queue = asyncio.Queue()
        self._ids = itertools.count(1)
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = self.component.drt.runtime.spawn(self._pump())

    async def _pump(self) -> None:
        while True:
            event: RouterEvent = await self._queue.get()
            try:
                await self.component.publish_event(KV_EVENT_SUBJECT, event.to_wire())
            except Exception:
                logger.exception("kv event publish failed")

    def publish_stored(self, block_hashes: List[int],
                       parent_hash: Optional[int],
                       tier: str = "hbm") -> None:
        self._queue.put_nowait(
            RouterEvent(
                worker_id=self.worker_id,
                stored=KvCacheStored(block_hashes=list(block_hashes), parent_hash=parent_hash),
                event_id=next(self._ids),
                tier=tier,
            )
        )

    def publish_removed(self, block_hashes: List[int],
                        tier: str = "hbm") -> None:
        self._queue.put_nowait(
            RouterEvent(
                worker_id=self.worker_id,
                removed=KvCacheRemoved(block_hashes=list(block_hashes)),
                event_id=next(self._ids),
                tier=tier,
            )
        )

    def as_sink(self) -> KvEventSink:
        """Adapter plugged into the engine's BlockAllocator. The cold
        hooks advertise cold-tier residency (kv/cold_tier.py spills and
        evictions) so routers score rehydratable prefixes discounted."""
        return KvEventSink(
            on_stored=self.publish_stored,
            on_removed=self.publish_removed,
            on_stored_cold=lambda hashes, parent: self.publish_stored(
                hashes, parent, tier="cold"),
            on_removed_cold=lambda hashes: self.publish_removed(
                hashes, tier="cold"),
        )

    def stop(self) -> None:
        if self._task:
            self._task.cancel()


class KvMetricsPublisher:
    """Exposes ForwardPassMetrics through the endpoint stats scrape.

    ``stats_handler()`` goes into Endpoint.serve(stats_handler=...); callers
    (KvMetricsAggregator) see it under the ``data`` key of scraped stats.
    """

    def __init__(self, metrics_fn: Callable[[], dict]):
        self.metrics_fn = metrics_fn

    def stats_handler(self) -> dict:
        raw = self.metrics_fn()
        out = ForwardPassMetrics.from_wire(raw).to_wire()
        # engine-specific extras (e.g. disagg remote-prefill counters) ride
        # along; consumers key off the ForwardPassMetrics fields they know
        for key, value in raw.items():
            out.setdefault(key, value)
        return out
