"""KV-aware worker selection.

Cost function and predicted-state updates mirror the reference's default
selector (reference: lib/llm/src/kv_router/scheduler.rs:238-340):

    logit = 2 * overlap_ratio - gpu_cache_usage - normalized_active_slots

Highest logit wins; ties break randomly. After each decision the chosen
worker's predicted load is bumped (active slots +1, kv blocks += newly
needed) so a burst of requests doesn't pile onto one worker between
metrics refreshes.
"""

from __future__ import annotations

import dataclasses
import logging
import math
import random
import time
from typing import Callable, Dict, List, Optional

from .indexer import OverlapScores
from .protocols import ForwardPassMetrics

logger = logging.getLogger(__name__)


class AllWorkersBusy(Exception):
    pass


@dataclasses.dataclass
class WorkerState:
    worker_id: str
    metrics: ForwardPassMetrics
    # predicted deltas since the last metrics refresh
    predicted_active: int = 0
    predicted_blocks: int = 0
    # monotonic time of the last metrics refresh; the cost function
    # skips workers whose snapshot exceeds the staleness bound
    updated_at: float = 0.0

    def cache_usage(self, block_size: int) -> float:
        total = self.metrics.kv_total_blocks or 1
        return min(
            1.0,
            (self.metrics.kv_active_blocks + self.predicted_blocks) / total,
        )

    def normalized_active(self) -> float:
        total = self.metrics.request_total_slots or 1
        return (self.metrics.request_active_slots + self.predicted_active) / total


class KvScheduler:
    def __init__(self, block_size: int = 16, require_free_slot: bool = False,
                 staleness_bound_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 cold_discount: float = 0.5):
        self.block_size = block_size
        self.require_free_slot = require_free_slot
        # cold-tier blocks (rehydratable spill files, kv/cold_tier.py)
        # count toward a worker's overlap at this fraction of a warm
        # block: a rehydrate pays disk + H2D instead of recompute, so it
        # is worth routing toward — but never as much as hot KV
        self.cold_discount = max(0.0, min(1.0, cold_discount))
        # snapshots older than this are not trusted by the cost function
        # (None/0 = off). A worker whose scrape stopped (wedged engine,
        # partitioned host) keeps its LAST load forever — typically a
        # low-looking one, so without the bound it becomes a black hole
        # every new request routes into.
        self.staleness_bound_s = staleness_bound_s or None
        self.clock = clock
        self.workers: Dict[str, WorkerState] = {}
        self.stale_skips = 0  # lifetime stale-worker exclusions
        self.draining_skips = 0  # lifetime draining-worker exclusions

    def update_metrics(self, worker_id: str, metrics: ForwardPassMetrics) -> None:
        now = self.clock()
        state = self.workers.get(worker_id)
        if state is None:
            self.workers[worker_id] = WorkerState(
                worker_id, metrics, updated_at=now)
        else:
            state.metrics = metrics
            state.predicted_active = 0
            state.predicted_blocks = 0
            state.updated_at = now

    def remove_worker(self, worker_id: str) -> None:
        self.workers.pop(worker_id, None)

    def schedule(
        self, isl_tokens: int, overlap: OverlapScores,
        pool: Optional[set] = None,
    ) -> "SchedulingDecision":
        """Pick a worker for a request with ``isl_tokens`` prompt tokens.

        ``pool`` restricts the decision to one model's workers (the
        per-model partition, registry/): ``model=`` selects the pool
        BEFORE prefix scoring, and overlap credit outside the pool is
        ignored — block hashes are token-based, so a same-prompt hit on
        a different model's worker is a different model's KV."""
        if not self.workers:
            raise AllWorkersBusy("no workers with metrics")
        total_blocks_needed = math.ceil(isl_tokens / self.block_size)

        # pool partition FIRST: workers outside the model's pool are a
        # structural exclusion, not a drain/staleness event — they must
        # not inflate those counters on every multi-pool decision
        in_pool = self.workers
        if pool is not None:
            in_pool = {wid: s for wid, s in self.workers.items()
                       if wid in pool}
            if not in_pool:
                raise AllWorkersBusy("no workers in the model's pool")
        # draining workers (recovery drain / rolling update) are out of
        # the pool outright — unlike staleness there is no fallback: a
        # drain is an explicit "send me nothing", and routing there
        # would hand the request straight to a migration
        candidates = {
            wid: s for wid, s in in_pool.items()
            if not getattr(s.metrics, "draining", False)
        }
        if len(candidates) < len(in_pool):
            self.draining_skips += len(in_pool) - len(candidates)
            logger.debug(
                "kv schedule: skipping %d draining worker(s): %s",
                len(in_pool) - len(candidates),
                sorted(set(in_pool) - set(candidates)),
            )
        if not candidates:
            raise AllWorkersBusy("all workers are draining")
        if self.staleness_bound_s:
            cutoff = self.clock() - self.staleness_bound_s
            fresh = {wid: s for wid, s in candidates.items()
                     if s.updated_at >= cutoff}
            if fresh and len(fresh) < len(candidates):
                self.stale_skips += len(candidates) - len(fresh)
                logger.debug(
                    "kv schedule: skipping %d stale worker(s): %s",
                    len(candidates) - len(fresh),
                    sorted(set(candidates) - set(fresh)),
                )
                candidates = fresh
            elif not fresh:
                # EVERY snapshot is stale (scrape loop hiccup) — routing
                # on old data beats refusing to route at all
                logger.warning(
                    "kv schedule: all %d worker snapshots exceed the "
                    "%.1fs staleness bound; routing on stale data",
                    len(self.workers), self.staleness_bound_s,
                )

        best: List[str] = []
        best_logit = -float("inf")
        details = {}
        for wid, state in candidates.items():
            if self.require_free_slot and (
                state.metrics.request_active_slots + state.predicted_active
                >= (state.metrics.request_total_slots or 1)
            ):
                continue
            matched = overlap.scores.get(wid, 0)
            cold = overlap.cold_scores.get(wid, 0)
            # cold blocks count discounted: rehydration beats recompute
            # but loses to hot KV at equal coverage
            effective = matched + self.cold_discount * cold
            overlap_ratio = (
                effective * self.block_size / isl_tokens
                if isl_tokens else 0.0
            )
            logit = (
                2.0 * overlap_ratio
                - state.cache_usage(self.block_size)
                - state.normalized_active()
            )
            details[wid] = (logit, matched)
            if logit > best_logit + 1e-9:
                best, best_logit = [wid], logit
            elif abs(logit - best_logit) <= 1e-9:
                best.append(wid)
        if not best:
            raise AllWorkersBusy("all workers at slot capacity")
        chosen = random.choice(best)
        matched = overlap.scores.get(chosen, 0)
        # predicted-state update (process_worker_selection analog): cold
        # blocks still allocate fresh HBM on rehydrate, so only the warm
        # match reduces the predicted block demand
        state = self.workers[chosen]
        state.predicted_active += 1
        state.predicted_blocks += max(0, total_blocks_needed - matched)
        logger.debug("kv schedule: %s logit=%.3f matched=%d", chosen, best_logit, matched)
        # the pull hint: the worker holding the LONGEST warm+cold prefix
        # overall, even when load steered the request elsewhere — the
        # chosen worker's fabric can pull the difference from it
        # (kv/fabric.py) instead of recomputing
        best_owner, best_owned, best_key = None, 0, (0.0, 0)
        for wid in set(overlap.scores) | set(overlap.cold_scores):
            if pool is not None and wid not in pool:
                # another model's worker: its "overlap" is a token-hash
                # coincidence, not pullable KV for this model
                continue
            warm_b = overlap.scores.get(wid, 0)
            cold_b = overlap.cold_scores.get(wid, 0)
            # rank with the same discount the cost function uses (a
            # rehydrate is cheaper than recompute but dearer than hot
            # KV); warm coverage breaks effective-score ties
            key = (warm_b + self.cold_discount * cold_b, warm_b)
            if key > best_key:
                best_owner, best_owned = wid, warm_b + cold_b
                best_key = key
        return SchedulingDecision(
            worker_id=chosen,
            matched_blocks=matched,
            prefix_hit_tokens=matched * self.block_size,
            isl_tokens=isl_tokens,
            cold_blocks=overlap.cold_scores.get(chosen, 0),
            best_prefix_worker=best_owner,
            best_prefix_blocks=best_owned,
        )


@dataclasses.dataclass
class SchedulingDecision:
    worker_id: str
    matched_blocks: int
    prefix_hit_tokens: int
    isl_tokens: int
    # cold-tier blocks the chosen worker can rehydrate (discount-scored)
    cold_blocks: int = 0
    # the pull hint: the worker holding the longest warm+cold prefix of
    # this prompt, even if load routed the request elsewhere — the
    # chosen worker's KV fabric pulls the difference from it
    best_prefix_worker: Optional[str] = None
    best_prefix_blocks: int = 0

    @property
    def overlap_ratio(self) -> float:
        return self.prefix_hit_tokens / self.isl_tokens if self.isl_tokens else 0.0
