"""RouterEvent recorder/replay: offline router-policy evaluation.

Capture production KV events to JSONL, replay them later into a fresh
indexer to evaluate routing policies without a cluster. Reference analog:
lib/llm/src/recorder.rs + kv_router/recorder.rs.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Iterator, Optional, Union

import msgpack

from ..runtime.component import Component
from .indexer import KvIndexer, ShardedKvIndexer
from .protocols import KV_EVENT_SUBJECT, RouterEvent

logger = logging.getLogger(__name__)


class KvRecorder:
    """Subscribes to an endpoint's kv_events and appends them to JSONL."""

    def __init__(
        self,
        component: Component,
        path: str,
        max_bytes: Optional[int] = None,
    ):
        self.component = component
        self.path = path
        self.max_bytes = max_bytes
        self.count = 0
        self._task = None
        self._sub = None
        self._fh = None

    async def start(self) -> "KvRecorder":
        self._fh = open(self.path, "a")
        self._sub = await self.component.subscribe_event(KV_EVENT_SUBJECT)
        self._task = self.component.drt.runtime.spawn(self._consume())
        return self

    async def _consume(self) -> None:
        async for msg in self._sub:
            try:
                event = msgpack.unpackb(msg.payload, raw=False)
                self._fh.write(json.dumps({"ts": time.time(), "event": event}) + "\n")
                self._fh.flush()
                self.count += 1
                if self.max_bytes and self._fh.tell() > self.max_bytes:
                    self._rotate()
            except Exception:
                logger.exception("record failed")

    def _rotate(self) -> None:
        self._fh.close()
        os.rename(self.path, f"{self.path}.{int(time.time())}")
        self._fh = open(self.path, "a")

    async def stop(self) -> None:
        if self._sub:
            self._sub.cancel()
        if self._task:
            self._task.cancel()
        if self._fh:
            self._fh.close()


def iter_recorded_events(path: str) -> Iterator[RouterEvent]:
    with open(path) as f:
        for line in f:
            if line.strip():
                yield RouterEvent.from_wire(json.loads(line)["event"])


def replay_events(
    path: str,
    indexer: Union[KvIndexer, ShardedKvIndexer],
    timed: bool = False,
) -> int:
    """Feed recorded events into an indexer; returns the event count."""
    n = 0
    last_ts = None
    for line in open(path):
        if not line.strip():
            continue
        rec = json.loads(line)
        if timed and last_ts is not None:
            time.sleep(max(0.0, min(1.0, rec["ts"] - last_ts)))
        last_ts = rec["ts"]
        indexer.apply_event(RouterEvent.from_wire(rec["event"]))
        n += 1
    return n
