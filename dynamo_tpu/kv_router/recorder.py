"""RouterEvent recorder/replay: offline router-policy evaluation.

Capture production KV events to JSONL, replay them later into a fresh
indexer to evaluate routing policies without a cluster. Reference analog:
lib/llm/src/recorder.rs + kv_router/recorder.rs.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import logging
import os
import time
from typing import Iterator, Optional, Union

import msgpack

from ..runtime.component import Component
from .indexer import KvIndexer, ShardedKvIndexer
from .protocols import KV_EVENT_SUBJECT, RouterEvent

logger = logging.getLogger(__name__)


class KvRecorder:
    """Subscribes to an endpoint's kv_events and appends them to JSONL."""

    def __init__(
        self,
        component: Component,
        path: str,
        max_bytes: Optional[int] = None,
    ):
        self.component = component
        self.path = path
        self.max_bytes = max_bytes
        self.count = 0
        self._task = None
        self._sub = None
        self._fh = None
        # single dedicated writer thread: every file op (open, write,
        # rotate, close) goes through it in submission order, so stop()
        # can never close the handle under an in-flight write
        self._io: Optional[concurrent.futures.ThreadPoolExecutor] = None

    async def start(self) -> "KvRecorder":
        # file IO runs off-loop: this recorder shares the event loop with
        # the router hot path, and an open() or flush() against a slow
        # (network) filesystem must not stall it
        self._io = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="kv-recorder")
        loop = asyncio.get_running_loop()
        # dynlint: allow(cross-domain-race) - awaited before any write is submitted; happens-before every _io op
        self._fh = await loop.run_in_executor(self._io, open, self.path, "a")
        self._sub = await self.component.subscribe_event(KV_EVENT_SUBJECT)
        self._task = self.component.drt.runtime.spawn(self._consume())
        return self

    async def _consume(self) -> None:
        loop = asyncio.get_running_loop()
        async for msg in self._sub:
            try:
                event = msgpack.unpackb(msg.payload, raw=False)
                line = json.dumps({"ts": time.time(), "event": event}) + "\n"
                await loop.run_in_executor(self._io, self._write_line, line)
                self.count += 1
                # dynlint: allow(cross-domain-race) - the write/rotate just awaited completed; FIFO _io leaves no op in flight here
                if self.max_bytes and self._fh.tell() > self.max_bytes:
                    await loop.run_in_executor(self._io, self._rotate)
            except Exception:
                logger.exception("record failed")

    # every method below runs only on the single-worker FIFO _io
    # executor: submission order serializes open/write/rotate/close, so
    # the cross-domain writes dynrace sees are sequenced, never racing
    def _write_line(self, line: str) -> None:
        # dynlint: allow(cross-domain-race) - single-worker FIFO executor serializes all _fh ops
        self._fh.write(line)
        # dynlint: allow(cross-domain-race) - single-worker FIFO executor serializes all _fh ops
        self._fh.flush()

    def _rotate(self) -> None:
        # dynlint: allow(cross-domain-race) - single-worker FIFO executor serializes all _fh ops
        self._fh.close()
        os.rename(self.path, f"{self.path}.{int(time.time())}")
        # dynlint: allow(cross-domain-race) - single-worker FIFO executor serializes all _fh ops
        self._fh = open(self.path, "a")

    async def stop(self) -> None:
        if self._sub:
            self._sub.cancel()
        if self._task:
            self._task.cancel()
        if self._fh:
            # close through the writer thread, resolving self._fh AT RUN
            # time: FIFO ordering puts this after any queued write or
            # _rotate, and a rotate that raced shutdown swapped the handle
            # — binding self._fh.close here would close the old one and
            # leak the new
            await asyncio.get_running_loop().run_in_executor(
                self._io, self._close_fh)
        if self._io:
            self._io.shutdown(wait=False)
            self._io = None

    def _close_fh(self) -> None:
        # dynlint: allow(cross-domain-race) - single-worker FIFO executor serializes all _fh ops
        fh, self._fh = self._fh, None
        if fh is not None:
            fh.close()


def iter_recorded_events(path: str) -> Iterator[RouterEvent]:
    with open(path) as f:
        for line in f:
            if line.strip():
                yield RouterEvent.from_wire(json.loads(line)["event"])


def replay_events(
    path: str,
    indexer: Union[KvIndexer, ShardedKvIndexer],
    timed: bool = False,
) -> int:
    """Feed recorded events into an indexer; returns the event count."""
    n = 0
    last_ts = None
    for line in open(path):
        if not line.strip():
            continue
        rec = json.loads(line)
        if timed and last_ts is not None:
            time.sleep(max(0.0, min(1.0, rec["ts"] - last_ts)))
        last_ts = rec["ts"]
        indexer.apply_event(RouterEvent.from_wire(rec["event"]))
        n += 1
    return n
