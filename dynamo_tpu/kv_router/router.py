"""KvRouter: event-indexed, load-aware worker selection for one endpoint.

Ties together the indexer (fed by the workers' kv_events), the metrics
aggregator (stats scrape), and the scheduler cost function; publishes
KVHitRateEvents so observability tooling can track routing quality.

Reference analog: lib/llm/src/kv_router.rs:66-169.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional, Union

import msgpack

from ..runtime.client import Client
from ..runtime.component import Component
from ..telemetry.flight import flight_recorder
from ..telemetry.registry import MetricsRegistry
from ..tokens import compute_block_hashes
from .indexer import KvIndexer, ShardedKvIndexer
from .metrics_aggregator import KvMetricsAggregator
from .protocols import KV_EVENT_SUBJECT, KV_HIT_RATE_EVENT, RouterEvent
from .scheduler import KvScheduler, SchedulingDecision

logger = logging.getLogger(__name__)


class KvRouter:
    def __init__(
        self,
        component: Component,
        client: Client,
        block_size: int = 16,
        num_shards: int = 1,
        poll_interval: float = 0.1,
        staleness_bound_s: float = 0.0,
    ):
        self.component = component
        self.client = client
        self.block_size = block_size
        self.indexer: Union[KvIndexer, ShardedKvIndexer] = (
            KvIndexer(block_size) if num_shards <= 1 else ShardedKvIndexer(num_shards, block_size)
        )
        # staleness_bound_s > 0: the cost function skips workers whose
        # scraped snapshot is older than the bound (0 = trust forever)
        self.scheduler = KvScheduler(
            block_size, staleness_bound_s=staleness_bound_s or None)
        self.aggregator = KvMetricsAggregator(
            client,
            poll_interval=poll_interval,
            on_update=self.scheduler.update_metrics,
            on_remove=self._on_worker_gone,
            on_sync=self._sync_live_workers,
        )
        self._event_task: Optional[asyncio.Task] = None
        self._event_sub = None
        # the router's own observability surface: per-worker scraped load
        # (active blocks, prefix hit rate, scrape staleness) plus routing
        # decision counters — previously internal-only state
        self.registry = MetricsRegistry()
        self.aggregator.register_into(self.registry)
        self._decisions = self.registry.counter(
            "dynamo_kv_router_decisions_total",
            "Scheduling decisions, labelled by chosen worker",
        )
        self._overlap_blocks = self.registry.counter(
            "dynamo_kv_router_overlap_blocks_total",
            "Prefix-overlap blocks credited to chosen workers",
        )
        self._stale_skips = self.registry.counter(
            "dynamo_kv_router_stale_worker_skips_total",
            "Workers excluded from a scheduling decision because their "
            "load snapshot exceeded the staleness bound",
        )
        self._draining_skips = self.registry.counter(
            "dynamo_kv_router_draining_worker_skips_total",
            "Workers excluded from a scheduling decision because their "
            "load snapshot carried the recovery-drain flag",
        )

    def _on_worker_gone(self, worker_id: str) -> None:
        self.scheduler.remove_worker(worker_id)
        self.indexer.remove_worker(worker_id)

    def _sync_live_workers(self, live: set) -> None:
        """Purge index entries for workers that died before ever scraping."""
        for wid in set(self.indexer.worker_ids) - live:
            self.indexer.remove_worker(wid)

    async def start(self) -> "KvRouter":
        await self.client.start()
        self._event_sub = await self.component.subscribe_event(KV_EVENT_SUBJECT)
        self._event_task = self.component.drt.runtime.spawn(self._consume_events())
        self.aggregator.start()
        return self

    async def _consume_events(self) -> None:
        async for msg in self._event_sub:
            try:
                event = RouterEvent.from_wire(msgpack.unpackb(msg.payload, raw=False))
                self.indexer.apply_event(event)
            except Exception:
                logger.exception("bad kv event")

    def model_pool(self, model: Optional[str]) -> Optional[set]:
        """Instance ids registered as serving ``model`` (per-model pool
        partition). None = no filtering (no model named). Delegates to
        the client's eligibility predicate so routing and fallback
        picking can never diverge on wildcard semantics."""
        if model is None:
            return None
        return set(self.client.eligible_ids(model))

    async def schedule(self, token_ids, trace_id: Optional[str] = None,
                       model: Optional[str] = None) -> SchedulingDecision:
        """token ids → chosen worker instance id (+hit telemetry).
        ``trace_id`` rides the flight event so the pick is attributable
        in a request's cluster-stitched X-ray; ``model`` selects the
        per-model pool before prefix scoring."""
        hashes = compute_block_hashes(token_ids, self.block_size)
        overlap = self.indexer.find_matches(hashes)
        decision = self.scheduler.schedule(
            len(token_ids), overlap, pool=self.model_pool(model))
        # federation pattern: the scheduler counts exclusions; the series
        # mirrors its monotonic total (set_sample, not inc)
        self._stale_skips.set_sample(float(self.scheduler.stale_skips))
        self._draining_skips.set_sample(
            float(self.scheduler.draining_skips))
        self._decisions.inc(worker=str(decision.worker_id))
        self._overlap_blocks.inc(
            decision.matched_blocks, worker=str(decision.worker_id)
        )
        flight_recorder().record(
            "kv_router.pick", trace_id=trace_id,
            worker=str(decision.worker_id),
            isl_blocks=-(-len(token_ids) // self.block_size),
            overlap_blocks=decision.matched_blocks,
            cold_blocks=decision.cold_blocks,
            # the pull hint: where the longest warm+cold prefix lives —
            # when it differs from the chosen worker, the pick's cost
            # was a fabric pull away from a full hit (the chosen
            # worker's own ownership view drives the actual pull)
            best_prefix_worker=(str(decision.best_prefix_worker)
                                if decision.best_prefix_worker else None),
            best_prefix_blocks=decision.best_prefix_blocks,
        )
        try:
            await self.component.namespace.publish_event(
                KV_HIT_RATE_EVENT,
                {
                    "worker_id": decision.worker_id,
                    "isl_blocks": -(-len(token_ids) // self.block_size),
                    "overlap_blocks": decision.matched_blocks,
                },
            )
        except Exception:
            logger.debug("hit-rate event publish failed", exc_info=True)
        return decision

    async def stop(self) -> None:
        if self._event_sub is not None:
            self._event_sub.cancel()
        if self._event_task is not None:
            self._event_task.cancel()
        self.aggregator.stop()
