// Concurrency/memory stress driver for the native core, built by CI
// under -fsanitize=thread and -fsanitize=address,undefined
// (native/run_sanitizers.sh). Reference analog: the reference's
// sanitizer CI jobs over its native runtime (SURVEY.md §5 race
// detection); here the contract under test is the indexer's
// mutex-guarded tree (indexer.cc Tree::mu) and the hashing hot path.
//
// Exit code 0 = clean; sanitizer reports fail the process.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
uint64_t dt_xxh64(const void* data, size_t len, uint64_t seed);
size_t dt_compute_block_hashes(const uint32_t* tokens, size_t n_tokens,
                               size_t block_size, uint64_t seed,
                               uint64_t* out, size_t out_cap);
void* dt_tree_new(double expiration_s);
void dt_tree_free(void* tp);
void dt_tree_apply_stored(void* tp, const char* worker, int has_parent,
                          uint64_t parent, const uint64_t* hashes, size_t n);
void dt_tree_apply_removed(void* tp, const char* worker,
                           const uint64_t* hashes, size_t n);
void dt_tree_remove_worker(void* tp, const char* worker);
size_t dt_tree_size(void* tp);
size_t dt_tree_clear_expired(void* tp);
void* dt_tree_find_matches(void* tp, const uint64_t* hashes, size_t n,
                           int early_exit);
size_t dt_result_num_workers(void* rp);
const char* dt_result_worker(void* rp, size_t i);
uint32_t dt_result_score(void* rp, size_t i);
void dt_result_free(void* rp);
}

namespace {

constexpr int kThreads = 4;
constexpr int kIters = 2000;
constexpr size_t kChain = 8;

void worker_thread(void* tree, int tid, std::atomic<uint64_t>* checksum) {
  std::string worker = "worker-" + std::to_string(tid);
  std::vector<uint32_t> tokens(64);
  std::vector<uint64_t> hashes(kChain);
  for (int it = 0; it < kIters; ++it) {
    for (size_t i = 0; i < tokens.size(); ++i) {
      tokens[i] = static_cast<uint32_t>(tid * 1000003 + it * 31 + i);
    }
    size_t n = dt_compute_block_hashes(tokens.data(), tokens.size(), 8,
                                       1337, hashes.data(), hashes.size());
    dt_tree_apply_stored(tree, worker.c_str(), /*has_parent=*/0, 0,
                         hashes.data(), n);
    void* res = dt_tree_find_matches(tree, hashes.data(), n, /*early=*/0);
    for (size_t i = 0; i < dt_result_num_workers(res); ++i) {
      checksum->fetch_add(dt_result_score(res, i) +
                          std::strlen(dt_result_worker(res, i)));
    }
    dt_result_free(res);
    if (it % 7 == 0) {
      dt_tree_apply_removed(tree, worker.c_str(), hashes.data(), n / 2);
    }
    if (it % 251 == 250) {
      dt_tree_remove_worker(tree, worker.c_str());
    }
    checksum->fetch_add(dt_tree_size(tree));
    if (it % 97 == 0) {
      dt_tree_clear_expired(tree);
    }
  }
  dt_tree_remove_worker(tree, worker.c_str());
}

}  // namespace

int main() {
  // deterministic single-thread hashing sanity first
  const char msg[] = "dynamo-tpu";
  uint64_t h1 = dt_xxh64(msg, sizeof(msg) - 1, 0);
  uint64_t h2 = dt_xxh64(msg, sizeof(msg) - 1, 0);
  if (h1 != h2 || h1 == 0) {
    std::fprintf(stderr, "hash instability\n");
    return 1;
  }

  void* tree = dt_tree_new(/*expiration_s=*/0.5);
  std::atomic<uint64_t> checksum{0};
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back(worker_thread, tree, t, &checksum);
  }
  for (auto& th : threads) th.join();

  size_t final_size = dt_tree_size(tree);
  dt_tree_free(tree);
  std::printf("stress ok: checksum=%llu final_size=%zu\n",
              static_cast<unsigned long long>(checksum.load()), final_size);
  return 0;
}
