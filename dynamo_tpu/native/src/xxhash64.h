// XXH64 — 64-bit hash used for KV block / sequence hashing.
//
// Independent implementation of the public XXH64 algorithm (Yann Collet,
// BSD-licensed spec) so the native hot path produces bit-identical hashes to
// the Python fallback (python-xxhash's xxh64). The reference framework salts
// block hashes with a fixed seed the same way
// (reference: lib/llm/src/kv_router/indexer.rs:64, lib/tokens/src/lib.rs:16-120).
#pragma once

#include <cstddef>
#include <cstdint>
#include <cstring>

namespace dynamo_native {

namespace detail {
constexpr uint64_t kPrime1 = 0x9E3779B185EBCA87ULL;
constexpr uint64_t kPrime2 = 0xC2B2AE3D27D4EB4FULL;
constexpr uint64_t kPrime3 = 0x165667B19E3779F9ULL;
constexpr uint64_t kPrime4 = 0x85EBCA77C2B2AE63ULL;
constexpr uint64_t kPrime5 = 0x27D4EB2F165667C5ULL;

inline uint64_t rotl64(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }

inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, sizeof(v));
  return v;  // little-endian hosts only (x86-64 / aarch64)
}

inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, sizeof(v));
  return v;
}

inline uint64_t round64(uint64_t acc, uint64_t input) {
  acc += input * kPrime2;
  acc = rotl64(acc, 31);
  return acc * kPrime1;
}

inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  acc ^= round64(0, val);
  return acc * kPrime1 + kPrime4;
}
}  // namespace detail

inline uint64_t xxh64(const void* data, size_t len, uint64_t seed) {
  using namespace detail;
  const uint8_t* p = static_cast<const uint8_t*>(data);
  const uint8_t* const end = p + len;
  uint64_t h;

  if (len >= 32) {
    uint64_t v1 = seed + kPrime1 + kPrime2;
    uint64_t v2 = seed + kPrime2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - kPrime1;
    const uint8_t* const limit = end - 32;
    do {
      v1 = round64(v1, read64(p));
      v2 = round64(v2, read64(p + 8));
      v3 = round64(v3, read64(p + 16));
      v4 = round64(v4, read64(p + 24));
      p += 32;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = merge_round(h, v1);
    h = merge_round(h, v2);
    h = merge_round(h, v3);
    h = merge_round(h, v4);
  } else {
    h = seed + kPrime5;
  }

  h += static_cast<uint64_t>(len);

  while (p + 8 <= end) {
    h ^= round64(0, read64(p));
    h = rotl64(h, 27) * kPrime1 + kPrime4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= static_cast<uint64_t>(read32(p)) * kPrime1;
    h = rotl64(h, 23) * kPrime2 + kPrime3;
    p += 4;
  }
  while (p < end) {
    h ^= static_cast<uint64_t>(*p) * kPrime5;
    h = rotl64(h, 11) * kPrime1;
    ++p;
  }

  h ^= h >> 33;
  h *= kPrime2;
  h ^= h >> 29;
  h *= kPrime3;
  h ^= h >> 32;
  return h;
}

}  // namespace dynamo_native
