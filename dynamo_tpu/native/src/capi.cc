// C ABI for external-engine KV event injection.
//
// Lets a non-Python engine (C/C++ runtime embedding a TPU executor, or any
// third-party serving stack) publish KV-cache stored/removed events into the
// router plane without linking Python. Mirrors the reference's C bindings for
// TRT-LLM (reference: lib/bindings/c/src/lib.rs:16-373 — dynamo_llm_init,
// dynamo_kv_event_publish_stored/removed over static globals).
//
// Transport-neutral by design: events serialize to the RouterEvent JSON wire
// format (dynamo_tpu/kv_router/protocols.py) and are delivered to a
// registered sink callback — the Python side installs a ctypes callback that
// forwards to the messaging plane. Without a sink, events accumulate in a
// bounded queue drained via dt_capi_drain (pull mode).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>

#include "xxhash64.h"

namespace {

using SinkFn = void (*)(const char* json, void* user_data);

struct CApiState {
  std::mutex mu;
  bool initialized = false;
  std::string ns, component, worker_id;
  uint32_t kv_block_size = 16;
  uint64_t hash_seed = 1337;
  SinkFn sink = nullptr;
  void* sink_user_data = nullptr;
  std::deque<std::string> queue;  // pull-mode buffer when no sink registered
  size_t max_queue = 65536;
  uint64_t dropped = 0;
};

CApiState& state() {
  static CApiState s;
  return s;
}

// Deliver one serialized event. Must be entered with `lock` held; the sink
// callback is invoked AFTER releasing it — the Python trampoline acquires
// the GIL, and calling it under s.mu would deadlock against a GIL-holding
// thread blocked on s.mu (lock-order inversion mu→GIL vs GIL→mu).
void emit(CApiState& s, std::string json, std::unique_lock<std::mutex>& lock) {
  SinkFn sink = s.sink;
  void* user_data = s.sink_user_data;
  if (sink == nullptr) {
    if (s.queue.size() >= s.max_queue) {
      s.queue.pop_front();
      ++s.dropped;
    }
    s.queue.push_back(std::move(json));
    return;
  }
  lock.unlock();
  sink(json.c_str(), user_data);
}

// JSON string escaping for worker ids (quotes/backslashes/control chars)
std::string json_escape(const std::string& in) {
  std::string out;
  out.reserve(in.size());
  char buf[8];
  for (unsigned char c : in) {
    if (c == '"' || c == '\\') {
      out += '\\';
      out += static_cast<char>(c);
    } else if (c < 0x20) {
      std::snprintf(buf, sizeof(buf), "\\u%04x", c);
      out += buf;
    } else {
      out += static_cast<char>(c);
    }
  }
  return out;
}

void append_u64_array(std::string& out, const uint64_t* v, size_t n) {
  out += '[';
  char buf[32];
  for (size_t i = 0; i < n; ++i) {
    if (i) out += ',';
    std::snprintf(buf, sizeof(buf), "%llu", (unsigned long long)v[i]);
    out += buf;
  }
  out += ']';
}

}  // namespace

extern "C" {

// status codes: 0 ok, 1 already-initialized / not-initialized, 2 bad args
int dt_capi_init(const char* ns, const char* component, const char* worker_id,
                 uint32_t kv_block_size, uint64_t hash_seed) {
  if (ns == nullptr || component == nullptr || worker_id == nullptr ||
      kv_block_size == 0)
    return 2;
  CApiState& s = state();
  std::lock_guard<std::mutex> lock(s.mu);
  if (s.initialized) return 1;
  s.ns = ns;
  s.component = component;
  s.worker_id = json_escape(worker_id);
  s.kv_block_size = kv_block_size;
  s.hash_seed = hash_seed;
  s.initialized = true;
  return 0;
}

int dt_capi_shutdown() {
  CApiState& s = state();
  std::lock_guard<std::mutex> lock(s.mu);
  if (!s.initialized) return 1;
  s.initialized = false;
  s.sink = nullptr;
  s.queue.clear();
  return 0;
}

void dt_capi_set_sink(SinkFn sink, void* user_data) {
  CApiState& s = state();
  std::lock_guard<std::mutex> lock(s.mu);
  s.sink = sink;
  s.sink_user_data = user_data;
}

// Publish stored blocks. The engine hands raw token ids; block (and chained
// sequence) hashes are computed here so external engines never need to
// reimplement the hash scheme. parent_hash: pointer to the sequence hash of
// the preceding block, or NULL for a sequence head.
int dt_kv_event_publish_stored(uint64_t event_id, const uint32_t* token_ids,
                               size_t num_tokens, const uint64_t* parent_hash) {
  CApiState& s = state();
  std::unique_lock<std::mutex> lock(s.mu);
  if (!s.initialized) return 1;
  if (token_ids == nullptr || num_tokens == 0) return 2;

  size_t n_full = num_tokens / s.kv_block_size;
  if (n_full == 0) return 2;

  std::string json = "{\"worker_id\":\"" + s.worker_id + "\",\"event_id\":";
  char buf[32];
  std::snprintf(buf, sizeof(buf), "%llu", (unsigned long long)event_id);
  json += buf;
  json += ",\"stored\":{\"block_hashes\":[";
  bool have_parent = parent_hash != nullptr;
  uint64_t parent = have_parent ? *parent_hash : 0;
  for (size_t i = 0; i < n_full; ++i) {
    uint64_t bh = dynamo_native::xxh64(token_ids + i * s.kv_block_size,
                                       s.kv_block_size * sizeof(uint32_t),
                                       s.hash_seed);
    if (have_parent) {
      uint64_t chain[2] = {parent, bh};
      parent = dynamo_native::xxh64(chain, sizeof(chain), 0);
    } else {
      parent = bh;
      have_parent = true;
    }
    if (i) json += ',';
    std::snprintf(buf, sizeof(buf), "%llu", (unsigned long long)parent);
    json += buf;
  }
  json += "],\"parent_hash\":";
  if (parent_hash != nullptr) {
    std::snprintf(buf, sizeof(buf), "%llu", (unsigned long long)*parent_hash);
    json += buf;
  } else {
    json += "null";
  }
  json += "}}";
  emit(s, std::move(json), lock);
  return 0;
}

int dt_kv_event_publish_removed(uint64_t event_id, const uint64_t* block_hashes,
                                size_t num_blocks) {
  CApiState& s = state();
  std::unique_lock<std::mutex> lock(s.mu);
  if (!s.initialized) return 1;
  if (block_hashes == nullptr || num_blocks == 0) return 2;

  std::string json = "{\"worker_id\":\"" + s.worker_id + "\",\"event_id\":";
  char buf[32];
  std::snprintf(buf, sizeof(buf), "%llu", (unsigned long long)event_id);
  json += buf;
  json += ",\"removed\":{\"block_hashes\":";
  append_u64_array(json, block_hashes, num_blocks);
  json += "}}";
  emit(s, std::move(json), lock);
  return 0;
}

// Pull mode: copy the oldest queued event into out (NUL-terminated).
// Returns the event's byte length (excluding NUL), 0 if the queue is empty,
// or -1 if cap is too small (event stays queued).
long dt_capi_drain(char* out, size_t cap) {
  CApiState& s = state();
  std::lock_guard<std::mutex> lock(s.mu);
  if (s.queue.empty()) return 0;
  const std::string& front = s.queue.front();
  if (front.size() + 1 > cap) return -1;
  std::memcpy(out, front.data(), front.size());
  out[front.size()] = '\0';
  long n = static_cast<long>(front.size());
  s.queue.pop_front();
  return n;
}

uint64_t dt_capi_dropped_events() {
  CApiState& s = state();
  std::lock_guard<std::mutex> lock(s.mu);
  return s.dropped;
}

}  // extern "C"
