// Native radix-tree KV indexer + token block hashing — the KV router's two
// hot paths (per-request hashing + prefix matching over the global index),
// implemented in C++ with a flat C API consumed via ctypes.
//
// Semantics mirror dynamo_tpu/kv_router/indexer.py (the pure-Python fallback)
// exactly — tests assert bit-identical scores on randomized event streams.
// Reference analog: the dedicated-thread Rust radix actor at
// reference lib/llm/src/kv_router/indexer.rs:239-379 (find_matches /
// apply_event / remove_worker) — here a mutex-guarded tree the caller's
// event loop owns, since the Python runtime is asyncio-confined.

#include <chrono>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "xxhash64.h"

namespace {

using Clock = std::chrono::steady_clock;

double now_s() {
  return std::chrono::duration<double>(Clock::now().time_since_epoch()).count();
}

struct Node {
  uint64_t hash = 0;
  Node* parent = nullptr;
  std::unordered_map<uint64_t, Node*> children;
  std::unordered_set<uint32_t> workers;  // interned worker ids
  double last_update = 0.0;
};

struct MatchResult {
  std::vector<std::pair<std::string, uint32_t>> scores;  // worker → depth
  std::vector<uint32_t> frequencies;                     // holders per depth
};

struct Tree {
  Node root;
  std::unordered_map<uint64_t, Node*> lookup;
  double expiration_s = -1.0;  // <0: disabled
  std::mutex mu;

  // worker-id interning (ids cross the C boundary as strings)
  std::unordered_map<std::string, uint32_t> worker_ids;
  std::vector<std::string> worker_names;

  ~Tree() {
    for (auto& [h, n] : lookup) delete n;
  }

  uint32_t intern(const char* worker) {
    auto it = worker_ids.find(worker);
    if (it != worker_ids.end()) return it->second;
    uint32_t id = static_cast<uint32_t>(worker_names.size());
    worker_names.emplace_back(worker);
    worker_ids.emplace(worker_names.back(), id);
    return id;
  }

  void prune(Node* node) {
    while (node != nullptr && node != &root) {
      if (!node->workers.empty() || !node->children.empty()) break;
      Node* parent = node->parent;
      if (parent != nullptr) parent->children.erase(node->hash);
      lookup.erase(node->hash);
      delete node;
      node = parent;
    }
  }

  void apply_stored(uint32_t worker, bool has_parent, uint64_t parent_hash,
                    const uint64_t* hashes, size_t n) {
    Node* parent = &root;
    if (has_parent) {
      auto it = lookup.find(parent_hash);
      // unknown parent (dropped/expired) → root the chain so the blocks stay
      // discoverable standalone — same recovery as the Python tree
      if (it != lookup.end()) parent = it->second;
    }
    double now = now_s();
    for (size_t i = 0; i < n; ++i) {
      uint64_t h = hashes[i];
      Node* node;
      auto it = lookup.find(h);
      if (it == lookup.end()) {
        node = new Node();
        node->hash = h;
        node->parent = parent;
        parent->children.emplace(h, node);
        lookup.emplace(h, node);
      } else {
        node = it->second;
        if (node->parent == &root && parent != &root) {
          // orphan-rooted earlier (parent event late/dropped) — re-link under
          // the real parent so prefix walks see the full chain
          root.children.erase(h);
          node->parent = parent;
          parent->children.emplace(h, node);
        }
      }
      node->workers.insert(worker);
      node->last_update = now;
      parent = node;
    }
  }

  void apply_removed(uint32_t worker, const uint64_t* hashes, size_t n) {
    for (size_t i = 0; i < n; ++i) {
      auto it = lookup.find(hashes[i]);
      if (it == lookup.end()) continue;
      Node* node = it->second;
      node->workers.erase(worker);
      if (node->workers.empty() && node->children.empty()) prune(node);
    }
  }

  void remove_worker(uint32_t worker) {
    std::vector<Node*> dead;
    for (auto& [h, node] : lookup) {
      node->workers.erase(worker);
      if (node->workers.empty() && node->children.empty()) dead.push_back(node);
    }
    for (Node* node : dead) prune(node);
  }

  MatchResult find_matches(const uint64_t* hashes, size_t n, bool early_exit) {
    MatchResult out;
    // per-worker consecutive-match score, keyed by interned id
    std::unordered_map<uint32_t, uint32_t> scores;
    Node* node = &root;
    double now = now_s();
    std::unordered_set<uint32_t> active;
    bool first = true;
    for (size_t i = 0; i < n; ++i) {
      auto it = node->children.find(hashes[i]);
      if (it == node->children.end()) break;
      Node* child = it->second;
      if (expiration_s >= 0.0 && now - child->last_update > expiration_s) break;
      if (first) {
        active = child->workers;
        first = false;
      } else {
        for (auto ait = active.begin(); ait != active.end();) {
          if (!child->workers.count(*ait)) ait = active.erase(ait);
          else ++ait;
        }
      }
      if (active.empty()) break;
      for (uint32_t w : active) scores[w] += 1;
      out.frequencies.push_back(static_cast<uint32_t>(child->workers.size()));
      if (early_exit && active.size() == 1) {
        uint32_t only = *active.begin();
        Node* nn = child;
        for (size_t j = out.frequencies.size(); j < n; ++j) {
          auto jt = nn->children.find(hashes[j]);
          if (jt == nn->children.end() || !jt->second->workers.count(only)) break;
          nn = jt->second;
          scores[only] += 1;
          out.frequencies.push_back(static_cast<uint32_t>(nn->workers.size()));
        }
        break;
      }
      node = child;
    }
    out.scores.reserve(scores.size());
    for (auto& [w, s] : scores) out.scores.emplace_back(worker_names[w], s);
    return out;
  }

  size_t clear_expired() {
    if (expiration_s < 0.0) return 0;
    double cutoff = now_s() - expiration_s;
    std::vector<Node*> dead;
    for (auto& [h, node] : lookup)
      if (node->last_update < cutoff && node->children.empty()) dead.push_back(node);
    for (Node* node : dead) prune(node);
    return dead.size();
  }
};

}  // namespace

extern "C" {

// ---- hashing -------------------------------------------------------------

uint64_t dt_xxh64(const void* data, size_t len, uint64_t seed) {
  return dynamo_native::xxh64(data, len, seed);
}

// Chained sequence hashes over complete blocks of uint32 token ids — the
// router hot path (Python fallback: dynamo_tpu/tokens.py compute_block_hashes;
// reference: lib/llm/src/kv_router/indexer.rs:123). Returns #hashes written.
size_t dt_compute_block_hashes(const uint32_t* tokens, size_t n_tokens,
                               size_t block_size, uint64_t seed,
                               uint64_t* out /* cap n_tokens/block_size */) {
  if (block_size == 0) return 0;
  size_t n_full = n_tokens / block_size;
  bool have_parent = false;
  uint64_t parent = 0;
  for (size_t i = 0; i < n_full; ++i) {
    uint64_t bh = dynamo_native::xxh64(tokens + i * block_size,
                                       block_size * sizeof(uint32_t), seed);
    if (have_parent) {
      uint64_t buf[2] = {parent, bh};
      parent = dynamo_native::xxh64(buf, sizeof(buf), 0);
    } else {
      parent = bh;
      have_parent = true;
    }
    out[i] = parent;
  }
  return n_full;
}

// ---- radix tree ----------------------------------------------------------

void* dt_tree_new(double expiration_s /* <0: disabled */) {
  Tree* t = new Tree();
  t->expiration_s = expiration_s;
  return t;
}

void dt_tree_free(void* tp) { delete static_cast<Tree*>(tp); }

void dt_tree_apply_stored(void* tp, const char* worker, int has_parent,
                          uint64_t parent_hash, const uint64_t* hashes,
                          size_t n) {
  Tree* t = static_cast<Tree*>(tp);
  std::lock_guard<std::mutex> lock(t->mu);
  t->apply_stored(t->intern(worker), has_parent != 0, parent_hash, hashes, n);
}

void dt_tree_apply_removed(void* tp, const char* worker, const uint64_t* hashes,
                           size_t n) {
  Tree* t = static_cast<Tree*>(tp);
  std::lock_guard<std::mutex> lock(t->mu);
  t->apply_removed(t->intern(worker), hashes, n);
}

void dt_tree_remove_worker(void* tp, const char* worker) {
  Tree* t = static_cast<Tree*>(tp);
  std::lock_guard<std::mutex> lock(t->mu);
  t->remove_worker(t->intern(worker));
}

size_t dt_tree_size(void* tp) {
  Tree* t = static_cast<Tree*>(tp);
  std::lock_guard<std::mutex> lock(t->mu);
  return t->lookup.size();
}

size_t dt_tree_clear_expired(void* tp) {
  Tree* t = static_cast<Tree*>(tp);
  std::lock_guard<std::mutex> lock(t->mu);
  return t->clear_expired();
}

void* dt_tree_find_matches(void* tp, const uint64_t* hashes, size_t n,
                           int early_exit) {
  Tree* t = static_cast<Tree*>(tp);
  std::lock_guard<std::mutex> lock(t->mu);
  return new MatchResult(t->find_matches(hashes, n, early_exit != 0));
}

size_t dt_result_num_workers(void* rp) {
  return static_cast<MatchResult*>(rp)->scores.size();
}

const char* dt_result_worker(void* rp, size_t i) {
  return static_cast<MatchResult*>(rp)->scores[i].first.c_str();
}

uint32_t dt_result_score(void* rp, size_t i) {
  return static_cast<MatchResult*>(rp)->scores[i].second;
}

size_t dt_result_num_freqs(void* rp) {
  return static_cast<MatchResult*>(rp)->frequencies.size();
}

uint32_t dt_result_freq(void* rp, size_t i) {
  return static_cast<MatchResult*>(rp)->frequencies[i];
}

void dt_result_free(void* rp) { delete static_cast<MatchResult*>(rp); }

}  // extern "C"
