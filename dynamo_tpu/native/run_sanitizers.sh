#!/usr/bin/env bash
# Build and run the native-core stress test under ASan+UBSan and TSan.
# (Reference analog: the sanitizer CI over the reference's native
# runtime; SURVEY.md §5 race detection.) Used by .github/workflows/ci.yml
# and runnable locally:  bash dynamo_tpu/native/run_sanitizers.sh
set -euo pipefail
cd "$(dirname "$0")"

CXX=${CXX:-g++}
SRCS="src/indexer.cc src/capi.cc src/stress_test.cc"
mkdir -p _build

echo "== asan+ubsan =="
$CXX -std=c++17 -O1 -g -fno-omit-frame-pointer \
    -fsanitize=address,undefined $SRCS -o _build/stress_asan -lpthread
./_build/stress_asan

echo "== tsan =="
$CXX -std=c++17 -O1 -g -fno-omit-frame-pointer \
    -fsanitize=thread $SRCS -o _build/stress_tsan -lpthread
./_build/stress_tsan

echo "sanitizers clean"
