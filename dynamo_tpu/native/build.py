"""On-demand build of the native C++ core.

Compiles ``src/*.cc`` into ``_build/libdynamo_native.so`` with the system
g++ the first time the package is imported (and whenever a source file
changes — staleness is a content hash over the sources baked into the
output filename). No pip/cmake dependency; plain ``g++ -O2 -shared``.

The reference ships its native core prebuilt by cargo (reference:
lib/runtime, lib/llm Rust crates); here the toolchain contract is just a
C++17 compiler, and every consumer degrades to the pure-Python fallbacks
when none is present.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
from pathlib import Path
from typing import Optional

def disabled_by_env() -> bool:
    """Operator kill-switch: DYNAMO_TPU_NATIVE=0 forces pure Python
    everywhere (hashing AND indexer — single source of truth for both
    dispatch sites)."""
    return os.environ.get("DYNAMO_TPU_NATIVE", "1").lower() in (
        "0", "false", "off", "no",
    )


_SRC_DIR = Path(__file__).parent / "src"
_BUILD_DIR = Path(__file__).parent / "_build"
_SOURCES = ("indexer.cc", "capi.cc")
_HEADERS = ("xxhash64.h",)


def _source_digest() -> str:
    h = hashlib.sha256()
    for name in _SOURCES + _HEADERS:
        h.update((_SRC_DIR / name).read_bytes())
    return h.hexdigest()[:16]


def lib_path() -> Path:
    return _BUILD_DIR / f"libdynamo_native-{_source_digest()}.so"


def build(verbose: bool = False) -> Optional[Path]:
    """Compile if stale; returns the .so path or None when no compiler."""
    try:
        out = lib_path()
        if out.exists():
            return out
        _BUILD_DIR.mkdir(exist_ok=True)
    except OSError:
        # read-only install / unreadable sources — degrade to pure Python
        return None
    cxx = os.environ.get("CXX", "g++")
    # compile to a process-unique temp name, then atomically rename: several
    # workers may race the first build of the same digest at import time
    tmp = out.with_suffix(f".tmp{os.getpid()}")
    cmd = [
        cxx, "-std=c++17", "-O2", "-fPIC", "-shared",
        "-Wall", "-Wextra",
        *(str(_SRC_DIR / s) for s in _SOURCES),
        "-I", str(_SRC_DIR),
        "-o", str(tmp),
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            if verbose:
                print(proc.stderr)
            return None
        os.replace(tmp, out)
    except (OSError, subprocess.TimeoutExpired):
        return None
    finally:
        tmp.unlink(missing_ok=True)
    # drop stale builds — only finished .so files; .tmp<pid> may be another
    # process's in-progress compile (crash leftovers are tiny and harmless)
    for old in _BUILD_DIR.glob("libdynamo_native-*.so"):
        if old != out:
            try:
                old.unlink()
            except OSError:
                pass
    return out


if __name__ == "__main__":
    path = build(verbose=True)
    print(path if path else "build failed")
    raise SystemExit(0 if path else 1)
