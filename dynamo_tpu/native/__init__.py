"""ctypes bindings over the native C++ core (hashing, radix indexer, C ABI).

Loads ``libdynamo_native.so`` (built on demand by build.py) and exposes:

- ``compute_block_hashes(tokens, block_size, seed)`` — batched chained
  block hashing, bit-identical to the pure-Python path in
  dynamo_tpu/tokens.py (both are XXH64; the native side is validated
  against python-xxhash in tests).
- ``NativeRadixTree`` — drop-in for kv_router.indexer.RadixTree's hot
  surface (apply_event / find_matches / remove_worker).
- ``CApi`` — the external-engine KV event ABI (reference analog:
  lib/bindings/c/src/lib.rs), with a Python sink callback.

Everything degrades gracefully: ``available()`` is False when no C++
toolchain exists and callers fall back to pure Python. Set
``DYNAMO_TPU_NATIVE=0`` to force pure Python everywhere. The first use per
source digest compiles on demand (can take tens of seconds); run
``python -m dynamo_tpu.native.build`` at deploy time to prebuild so worker
startup never pays it.
"""

from __future__ import annotations

import ctypes
import json
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

from . import build as _build
from .build import disabled_by_env

_lib = None
_lib_err: Optional[str] = None
_load_lock = threading.Lock()


def _declare(lib: ctypes.CDLL) -> None:
    u64p = ctypes.POINTER(ctypes.c_uint64)
    u32p = ctypes.POINTER(ctypes.c_uint32)

    lib.dt_xxh64.restype = ctypes.c_uint64
    lib.dt_xxh64.argtypes = [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint64]

    lib.dt_compute_block_hashes.restype = ctypes.c_size_t
    lib.dt_compute_block_hashes.argtypes = [
        u32p, ctypes.c_size_t, ctypes.c_size_t, ctypes.c_uint64, u64p,
    ]

    lib.dt_tree_new.restype = ctypes.c_void_p
    lib.dt_tree_new.argtypes = [ctypes.c_double]
    lib.dt_tree_free.argtypes = [ctypes.c_void_p]
    lib.dt_tree_apply_stored.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_uint64,
        u64p, ctypes.c_size_t,
    ]
    lib.dt_tree_apply_removed.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, u64p, ctypes.c_size_t,
    ]
    lib.dt_tree_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.dt_tree_size.restype = ctypes.c_size_t
    lib.dt_tree_size.argtypes = [ctypes.c_void_p]
    lib.dt_tree_clear_expired.restype = ctypes.c_size_t
    lib.dt_tree_clear_expired.argtypes = [ctypes.c_void_p]
    lib.dt_tree_find_matches.restype = ctypes.c_void_p
    lib.dt_tree_find_matches.argtypes = [
        ctypes.c_void_p, u64p, ctypes.c_size_t, ctypes.c_int,
    ]
    lib.dt_result_num_workers.restype = ctypes.c_size_t
    lib.dt_result_num_workers.argtypes = [ctypes.c_void_p]
    lib.dt_result_worker.restype = ctypes.c_char_p
    lib.dt_result_worker.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.dt_result_score.restype = ctypes.c_uint32
    lib.dt_result_score.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.dt_result_num_freqs.restype = ctypes.c_size_t
    lib.dt_result_num_freqs.argtypes = [ctypes.c_void_p]
    lib.dt_result_freq.restype = ctypes.c_uint32
    lib.dt_result_freq.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
    lib.dt_result_free.argtypes = [ctypes.c_void_p]

    lib.dt_capi_init.restype = ctypes.c_int
    lib.dt_capi_init.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_uint32, ctypes.c_uint64,
    ]
    lib.dt_capi_shutdown.restype = ctypes.c_int
    lib.dt_capi_set_sink.argtypes = [_SINK_CFUNC, ctypes.c_void_p]
    lib.dt_kv_event_publish_stored.restype = ctypes.c_int
    lib.dt_kv_event_publish_stored.argtypes = [
        ctypes.c_uint64, u32p, ctypes.c_size_t, u64p,
    ]
    lib.dt_kv_event_publish_removed.restype = ctypes.c_int
    lib.dt_kv_event_publish_removed.argtypes = [
        ctypes.c_uint64, u64p, ctypes.c_size_t,
    ]
    lib.dt_capi_drain.restype = ctypes.c_long
    lib.dt_capi_drain.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.dt_capi_dropped_events.restype = ctypes.c_uint64


_SINK_CFUNC = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p)


def _load():
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    with _load_lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        path = _build.build()
        if path is None:
            _lib_err = "native build unavailable (no C++ toolchain?)"
            return None
        try:
            lib = ctypes.CDLL(str(path))
            _declare(lib)
        except OSError as e:  # pragma: no cover
            _lib_err = str(e)
            return None
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def xxh64(data: bytes, seed: int = 0) -> int:
    lib = _load()
    assert lib is not None
    return int(lib.dt_xxh64(data, len(data), ctypes.c_uint64(seed)))


def _as_u64_array(hashes: Sequence[int]) -> np.ndarray:
    return np.asarray(hashes, dtype=np.uint64)


def compute_block_hashes(
    token_ids: Sequence[int], block_size: int, seed: int
) -> List[int]:
    """Chained sequence hashes of complete blocks — native hot path."""
    lib = _load()
    assert lib is not None
    tokens = np.ascontiguousarray(token_ids, dtype=np.uint32)
    n_full = len(tokens) // block_size if block_size > 0 else 0
    out = np.empty(n_full, dtype=np.uint64)
    n = lib.dt_compute_block_hashes(
        tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        len(tokens), block_size, ctypes.c_uint64(seed),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
    )
    return [int(h) for h in out[:n]]


class NativeRadixTree:
    """C++ radix tree with the RadixTree hot surface.

    find_matches returns ``(scores: dict[str, int], frequencies: list[int])``;
    kv_router.indexer wraps it into OverlapScores.
    """

    def __init__(self, expiration_s: Optional[float] = None):
        lib = _load()
        if lib is None:
            raise RuntimeError(_lib_err or "native core unavailable")
        self._lib = lib
        self._ptr = lib.dt_tree_new(
            ctypes.c_double(-1.0 if expiration_s is None else expiration_s)
        )

    def __del__(self):
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr and getattr(self, "_lib", None) is not None:
            self._lib.dt_tree_free(ptr)

    def apply_stored(
        self, worker_id: str, parent_hash: Optional[int], block_hashes: Sequence[int]
    ) -> None:
        arr = _as_u64_array(block_hashes)
        self._lib.dt_tree_apply_stored(
            self._ptr, worker_id.encode(),
            0 if parent_hash is None else 1,
            ctypes.c_uint64(parent_hash or 0),
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(arr),
        )

    def apply_removed(self, worker_id: str, block_hashes: Sequence[int]) -> None:
        arr = _as_u64_array(block_hashes)
        self._lib.dt_tree_apply_removed(
            self._ptr, worker_id.encode(),
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(arr),
        )

    def remove_worker(self, worker_id: str) -> None:
        self._lib.dt_tree_remove_worker(self._ptr, worker_id.encode())

    def clear_expired(self) -> int:
        return int(self._lib.dt_tree_clear_expired(self._ptr))

    def find_matches(self, block_hashes: Sequence[int], early_exit: bool = False):
        arr = _as_u64_array(block_hashes)
        res = self._lib.dt_tree_find_matches(
            self._ptr,
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(arr), 1 if early_exit else 0,
        )
        try:
            scores = {
                self._lib.dt_result_worker(res, i).decode():
                    int(self._lib.dt_result_score(res, i))
                for i in range(self._lib.dt_result_num_workers(res))
            }
            freqs = [
                int(self._lib.dt_result_freq(res, i))
                for i in range(self._lib.dt_result_num_freqs(res))
            ]
        finally:
            self._lib.dt_result_free(res)
        return scores, freqs

    def __len__(self) -> int:
        return int(self._lib.dt_tree_size(self._ptr))


class CApi:
    """External-engine KV event ABI (reference: lib/bindings/c).

    Usage from Python (tests / in-process engines):
        capi = CApi(); capi.init("ns", "comp", "worker-0", kv_block_size=16)
        capi.set_sink(lambda event_dict: ...)
        capi.publish_stored(1, token_ids)
    A C/C++ engine calls the same dt_* symbols directly.
    """

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError(_lib_err or "native core unavailable")
        self._lib = lib
        self._sink_ref = None  # keep the ctypes callback alive

    def __del__(self):
        # the C global must not outlive the ctypes trampoline this object
        # holds — clear it so no dangling function pointer remains
        if getattr(self, "_sink_ref", None) is not None:
            try:
                self._lib.dt_capi_set_sink(None, None)
            # dynlint: allow(silent-except) - destructor at interpreter shutdown; nowhere to report
            except Exception:  # pragma: no cover - interpreter shutdown
                pass

    def init(self, namespace: str, component: str, worker_id: str,
             kv_block_size: int = 16, hash_seed: int = 1337) -> int:
        return int(self._lib.dt_capi_init(
            namespace.encode(), component.encode(), worker_id.encode(),
            kv_block_size, ctypes.c_uint64(hash_seed),
        ))

    def shutdown(self) -> int:
        self._sink_ref = None
        return int(self._lib.dt_capi_shutdown())

    def set_sink(self, fn: Optional[Callable[[dict], None]]) -> None:
        if fn is None:
            self._sink_ref = None
            self._lib.dt_capi_set_sink(None, None)
            return

        def trampoline(raw: bytes, _user):
            fn(json.loads(raw.decode()))

        self._sink_ref = _SINK_CFUNC(trampoline)
        self._lib.dt_capi_set_sink(self._sink_ref, None)

    def publish_stored(self, event_id: int, token_ids: Sequence[int],
                       parent_hash: Optional[int] = None) -> int:
        tokens = np.ascontiguousarray(token_ids, dtype=np.uint32)
        parent = (
            None if parent_hash is None
            else ctypes.pointer(ctypes.c_uint64(parent_hash))
        )
        return int(self._lib.dt_kv_event_publish_stored(
            ctypes.c_uint64(event_id),
            tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            len(tokens), parent,
        ))

    def publish_removed(self, event_id: int, block_hashes: Sequence[int]) -> int:
        arr = _as_u64_array(block_hashes)
        return int(self._lib.dt_kv_event_publish_removed(
            ctypes.c_uint64(event_id),
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), len(arr),
        ))

    def drain(self, cap: int = 1 << 20) -> Optional[dict]:
        # -1 = head event bigger than cap (stays queued) — grow and retry
        # so one oversized event can't wedge the queue
        cap = max(int(cap), 64)
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.dt_capi_drain(buf, cap)
            if n == 0:
                return None
            if n > 0:
                return json.loads(buf.value.decode())
            cap *= 2

    def dropped_events(self) -> int:
        return int(self._lib.dt_capi_dropped_events())
