"""Llama-family decoder, functional JAX, paged-KV, scan-over-layers.

Design notes (TPU-first):
- Pure functions over a params pytree; layer weights are stacked on a
  leading L axis and the transformer body is one ``lax.scan`` whose carry
  holds (hidden, kv_cache) — compile time is O(1) in depth and the donated
  cache updates in place.
- The same ``forward`` serves bucketed prefill (S>1) and decode (S=1):
  new K/V are scattered into the paged cache, then attention runs over
  gathered cache blocks (ops/attention.py). ``kv_width`` bounds how many
  blocks are gathered so prefill doesn't pay full-context gathers.
- GQA, RoPE, RMSNorm, SwiGLU per the Llama architecture. Weights load from
  HF safetensors via models/loader.py.

This module is the engine the reference never had natively (it delegated
GPU work to vLLM/SGLang — SURVEY.md §2.4); here the model IS part of the
framework.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..engine.config import ModelConfig
from ..ops.attention import attention, lane_pad, scatter_kv_stacked
from .quant import dense

Params = Dict[str, Any]
KVCache = Tuple[jax.Array, jax.Array]  # k, v: [L, N_blocks, bs, KVH, D]


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    norm = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    return (norm * weight.astype(jnp.float32)).astype(dtype)


def _yarn_mscale(factor: float, mscale: float = 1.0) -> float:
    if factor <= 1.0:
        return 1.0
    return 0.1 * mscale * math.log(factor) + 1.0


def rope_frequencies(
    head_dim: int, theta: float, scaling: Optional[dict] = None
) -> Tuple[jax.Array, float]:
    """(inverse rope frequencies, attention factor) with HF
    ``rope_scaling`` applied.

    "linear" divides all frequencies by the factor; "llama3" (Llama-3.1+)
    scales low-frequency bands with a smooth ramp between the high/low
    wavelength thresholds; "yarn" (DeepSeek-V2/V3 and NTK-extended
    models) blends interpolated and extrapolated frequencies over the
    beta_fast/beta_slow correction range and returns the mscale
    attention factor the rotation must be multiplied by (cos/sin
    scaling; q and k each carry it, so scores scale by its square —
    matching transformers' ROPE_INIT_FUNCTIONS and DeepSeek's
    mscale/mscale_all_dim variant exactly). Unknown types warn and load
    unscaled (degrades only beyond the original context window).
    """
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    )
    if not scaling:
        return inv_freq, 1.0
    kind = scaling.get("rope_type") or scaling.get("type")
    factor = float(scaling.get("factor", 1.0))
    if kind == "linear":
        return inv_freq / factor, 1.0
    if kind == "llama3":
        low = float(scaling.get("low_freq_factor", 1.0))
        high = float(scaling.get("high_freq_factor", 4.0))
        orig = float(scaling.get("original_max_position_embeddings", 8192))
        wavelen = 2.0 * jnp.pi / inv_freq
        smooth = (orig / wavelen - low) / (high - low)
        smooth = jnp.clip(smooth, 0.0, 1.0)
        scaled = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        return jnp.where(
            wavelen < orig / high, inv_freq,            # high freq: keep
            jnp.where(wavelen > orig / low, inv_freq / factor, scaled),
        ), 1.0
    if kind == "yarn":
        orig = float(scaling.get("original_max_position_embeddings", 4096))
        beta_fast = float(scaling.get("beta_fast", 32.0))
        beta_slow = float(scaling.get("beta_slow", 1.0))

        def correction_dim(num_rotations: float) -> float:
            return (head_dim / 2.0) * math.log(
                orig / (num_rotations * 2.0 * math.pi)
            ) / math.log(theta)

        low = max(math.floor(correction_dim(beta_fast)), 0)
        # transformers clamps to head_dim - 1 (not the D/2 frequency
        # count) — the ramp denominator must match HF exactly or every
        # mid-band blend shifts
        high = min(math.ceil(correction_dim(beta_slow)), head_dim - 1)
        if low == high:
            high += 0.001  # avoid a zero-width ramp
        ramp = jnp.clip(
            (jnp.arange(head_dim // 2, dtype=jnp.float32) - low)
            / (high - low), 0.0, 1.0,
        )
        extrapolation_w = 1.0 - ramp   # high-frequency dims: keep as-is
        inv = (inv_freq / factor) * (1.0 - extrapolation_w) \
            + inv_freq * extrapolation_w
        attention_factor = scaling.get("attention_factor")
        if attention_factor is None:
            mscale = float(scaling.get("mscale") or 0.0)
            mscale_all = float(scaling.get("mscale_all_dim") or 0.0)
            if mscale and mscale_all:
                # DeepSeek variant: ratio of the two mscale curves —
                # taken only when BOTH keys are present, exactly as
                # transformers' _compute_yarn_parameters does
                attention_factor = _yarn_mscale(factor, mscale) / _yarn_mscale(
                    factor, mscale_all
                )
            else:
                attention_factor = _yarn_mscale(factor)
        return inv, float(attention_factor)
    if kind in ("longrope", "su"):
        # handled in apply_rope: the short/long factor choice depends on
        # the call's sequence length (a traced value), not just config
        raise ValueError(
            "longrope is resolved inside apply_rope, not rope_frequencies"
        )
    if kind not in (None, "default"):
        import logging

        logging.getLogger(__name__).warning(
            "rope_scaling type %r not implemented; serving with unscaled "
            "frequencies (contexts beyond the original window degrade)",
            kind,
        )
    return inv_freq, 1.0


def _longrope_frequencies(d: int, theta: float, scaling: dict, positions,
                          seq_basis=None):
    """Phi-3 longrope (transformers _compute_longrope_parameters +
    dynamic_rope_update): per-dim short/long frequency rescaling, the
    profile chosen PER ROW by whether that sequence's covered context
    exceeds the pretraining window — a traced comparison, since one
    compiled program serves all lengths, and per-row so one long request
    cannot flip co-batched short requests onto the long profile. Keys
    roped while a sequence was still short keep their short-profile
    rotation as it grows — exactly what HF's cached generation does
    (dynamic_rope_update re-ropes only new positions). The attention
    factor sqrt(1 + ln(len_ratio)/ln(original)) rides cos/sin regardless
    of profile, as HF applies it.

    ``seq_basis`` [B] is each row's covered context length (the engine
    passes context_lens); without it, each row's max position stands in.
    """
    missing = [k for k in ("short_factor", "long_factor") if k not in scaling]
    if missing or "original_max_position_embeddings" not in scaling:
        raise ValueError(
            f"longrope rope_scaling needs short_factor/long_factor and "
            f"original_max_position_embeddings (missing: "
            f"{missing + [k for k in ['original_max_position_embeddings'] if k not in scaling]}); "
            "ModelConfig.from_hf_config injects the window fields from "
            "the checkpoint config"
        )
    original = scaling["original_max_position_embeddings"]
    maxpos = scaling.get("max_position_embeddings", original)
    factor = maxpos / original
    attn_factor = scaling.get("attention_factor") or (
        1.0 if factor <= 1.0
        else math.sqrt(1.0 + math.log(factor) / math.log(original))
    )
    base_pow = theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    short = jnp.asarray(scaling["short_factor"], jnp.float32)
    long = jnp.asarray(scaling["long_factor"], jnp.float32)
    if seq_basis is None:
        seq_basis = jnp.max(positions, axis=-1) + 1  # [B]
    is_long = (seq_basis > original)[:, None, None]   # [B, 1, 1]
    ext = jnp.where(is_long, long[None, None, :], short[None, None, :])
    return 1.0 / (ext * base_pow), float(attn_factor)  # [B, 1, D/2]


def apply_rope(
    x: jax.Array, positions: jax.Array, theta: float,
    scaling: Optional[dict] = None,
    seq_basis=None,  # [B] covered context per row (longrope profile choice)
) -> jax.Array:
    """x: [B, S, H, D]; positions: [B, S]. HF-style half-rotation RoPE.

    The yarn attention factor rides on cos/sin (as in transformers), so
    q·k scores carry its square without touching the softmax scale.
    """
    d = x.shape[-1]
    kind = (scaling or {}).get("rope_type", (scaling or {}).get("type"))
    if kind in ("longrope", "su"):
        # [B, 1, D/2] — per-row profile; broadcasts with positions below
        inv_freq, attn_factor = _longrope_frequencies(
            d, theta, scaling, positions, seq_basis
        )
    else:
        inv_freq, attn_factor = rope_frequencies(d, theta, scaling)  # [D/2]
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [B, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :] * attn_factor            # [B, S, 1, D/2]
    sin = jnp.sin(angles)[:, :, None, :] * attn_factor
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    """Random-init params with the right shapes/layout (tests, benchmarks)."""
    l, d_model = cfg.num_layers, cfg.hidden_size
    h, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    inter = cfg.intermediate_size
    keys = jax.random.split(key, 10)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (fan_in ** -0.5)).astype(dtype)

    layers = {
        "ln1": jnp.ones((l, d_model), dtype),
        "wq": w(keys[1], (l, d_model, h * hd), d_model),
        "wk": w(keys[2], (l, d_model, kvh * hd), d_model),
        "wv": w(keys[3], (l, d_model, kvh * hd), d_model),
        "wo": w(keys[4], (l, h * hd, d_model), h * hd),
        "ln2": jnp.ones((l, d_model), dtype),
        "w_gate": w(keys[5], (l, d_model, inter), d_model),
        "w_up": w(keys[6], (l, d_model, inter), d_model),
        "w_down": w(keys[7], (l, inter, d_model), inter),
    }
    if cfg.attention_bias:
        layers["bq"] = jnp.zeros((l, h * hd), dtype)
        layers["bk"] = jnp.zeros((l, kvh * hd), dtype)
        layers["bv"] = jnp.zeros((l, kvh * hd), dtype)
    params: Params = {
        "embed": w(keys[0], (cfg.vocab_size, d_model), d_model),
        "layers": layers,
        "final_norm": jnp.ones((d_model,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(keys[8], (d_model, cfg.vocab_size), d_model)
    return params


# attention-trunk specs shared by every family using decoder_forward
ATTN_LAYER_SPECS = {
    "ln1": P(),
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    "wo": P(None, "tp", None),
    "ln2": P(),
    # qkv biases follow their projection's output sharding
    "bq": P(None, "tp"),
    "bk": P(None, "tp"),
    "bv": P(None, "tp"),
    # per-head-dim q/k norms (Qwen3): shared across heads → replicated
    "q_norm": P(),
    "k_norm": P(),
}


def base_specs(params: Params) -> Dict:
    """Specs for the non-layer params (embed / final_norm / lm_head)."""
    specs: Dict = {"embed": P(), "final_norm": P()}
    if "lm_head" in params:
        specs["lm_head"] = P(None, "tp")
    return specs


def param_specs(params: Params) -> Dict:
    """PartitionSpecs mirroring the param pytree (Megatron TP layout)."""
    layer_specs = {
        **ATTN_LAYER_SPECS,
        "w_gate": P(None, None, "tp"),
        "w_up": P(None, None, "tp"),
        "w_down": P(None, "tp", None),
    }
    specs = base_specs(params)
    specs["layers"] = {k: layer_specs[k] for k in params["layers"]}
    return specs


def init_kv_cache(
    cfg: ModelConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16
) -> KVCache:
    # minor dim lane-padded: physically free (XLA tiles HBM to 128 lanes)
    # and required by the manual-DMA decode kernel (ops/attention.lane_pad)
    shape = (
        cfg.num_layers, num_blocks, block_size, cfg.num_kv_heads,
        lane_pad(cfg.head_dim),
    )
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def embed_tokens(params: Params, tokens: jax.Array) -> jax.Array:
    """Plain embedding lookup (Gemma overrides with its sqrt(d) scale)."""
    return params["embed"][tokens]


def _swiglu_mlp(x: jax.Array, layer_params) -> jax.Array:
    gate = jax.nn.silu(dense(x, layer_params["w_gate"]))
    return dense(gate * dense(x, layer_params["w_up"]), layer_params["w_down"])


def alternating_window(cfg, li, layer_offset=0):
    """Per-layer sliding window for families whose layer_types alternate
    sliding/full starting sliding at GLOBAL layer 0 (Gemma-2, GPT-OSS;
    the pattern is validated at config parse for gpt-oss). ``li`` may be
    traced (inside the layer scan); ``layer_offset`` is the stage's first
    global layer index under pipeline staging. None when the family has
    no window at all."""
    if not cfg.sliding_window:
        return None
    return jnp.where(
        (li + layer_offset) % 2 == 0, cfg.sliding_window, jnp.int32(1 << 30)
    )


def gather_kv_writes(k, v, slot_mapping, axis):
    """All-gather new K/V and their slots over a manual mesh axis whose
    members shard the batch rows while replicating the KV cache (the
    pipelined pp x dp program): every member must apply EVERY member's
    cache writes or the replicas diverge. Shared by the GQA and Gemma-2
    attention factories."""
    return (
        jax.lax.all_gather(k, axis, axis=0, tiled=True),
        jax.lax.all_gather(v, axis, axis=0, tiled=True),
        jax.lax.all_gather(slot_mapping, axis, axis=0, tiled=True),
    )


def qkv_prologue(cfg, x, layer_params, b, s, positions, seq_basis):
    """The per-layer QKV head: projections (+ Qwen2 biases), head
    reshape, Qwen3 per-head norms, RoPE. ONE implementation shared by
    the dense paged path, the sequence-parallel chunk path, and the
    cacheless embeddings trunk — the SP path's bit-identical-KV
    contract depends on these never drifting."""
    h_heads, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    q = dense(x, layer_params["wq"])
    k = dense(x, layer_params["wk"])
    v = dense(x, layer_params["wv"])
    if "bq" in layer_params:  # Qwen2-family qkv biases, pre-rope
        q = q + layer_params["bq"]
        k = k + layer_params["bk"]
        v = v + layer_params["bv"]
    q = q.reshape(b, s, h_heads, hd)
    k = k.reshape(b, s, kvh, hd)
    v = v.reshape(b, s, kvh, hd)
    if "q_norm" in layer_params:  # Qwen3-family per-head norms, pre-rope
        q = rms_norm(q, layer_params["q_norm"], cfg.rms_norm_eps)
        k = rms_norm(k, layer_params["k_norm"], cfg.rms_norm_eps)
    q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling,
                   seq_basis=seq_basis)
    k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling,
                   seq_basis=seq_basis)
    return q, k, v


def make_gqa_attn_fn(cfg, b, s, positions, slot_mapping, block_tables,
                     context_lens, mesh, kv_gather_axis=None,
                     layer_offset=0, tp_axis=None):
    """The standard attention block: QKV + RoPE, paged-KV scatter, GQA
    attention, output projection. Families with different attention (MLA,
    models/deepseek.py) plug their own via run_layers' attn_fn.

    ``kv_gather_axis``: inside a manual shard_map whose batch rows shard
    over that mesh axis while the KV cache stays replicated across it
    (the pipelined pp x dp program, parallel/pipeline.py), every member
    must apply EVERY member's cache writes or the replicas diverge — the
    new K/V and their slots are all-gathered over the axis before the
    scatter; attention still runs on the local rows only.

    ``layer_offset`` is part of the family attn-factory contract (the
    pipeline passes the stage's first GLOBAL layer index): this family
    has no per-layer-index semantics, so it is accepted and ignored —
    Gemma-2's window alternation is the consumer."""
    del layer_offset  # no global-layer-index semantics in this family
    del tp_axis  # qkv biases are tp-sharded; no replicated additive terms
    h_heads, hd = cfg.num_heads, cfg.head_dim

    def attn_fn(x, layer_params, k_all, v_all, li):
        q, k, v = qkv_prologue(cfg, x, layer_params, b, s, positions,
                               context_lens)

        # in-place scatter into the stacked cache + layer-indexed kernels:
        # no per-layer cache slice is ever materialized inside the scan
        if kv_gather_axis is not None:
            k_w, v_w, slots_w = gather_kv_writes(k, v, slot_mapping,
                                                 kv_gather_axis)
        else:
            k_w, v_w, slots_w = k, v, slot_mapping
        k_all, v_all = scatter_kv_stacked(k_all, v_all, k_w, v_w, slots_w, li)
        attn = attention(
            q, k_all, v_all, block_tables, positions, context_lens,
            impl=cfg.attention_impl, mesh=mesh, layer_idx=li,
            # mistral/phi3-style whole-model window (0 = full attention;
            # rides the XLA path — see ops/attention.py)
            sliding_window=cfg.sliding_window or None,
        )
        delta = dense(attn.reshape(b, s, h_heads * hd), layer_params["wo"])
        return delta, k_all, v_all

    return attn_fn


def make_sp_gqa_attn_fn(cfg, b, s, positions, slot_mapping, block_tables,
                        context_lens, chunk_start, mesh, sp_axis="sp",
                        head_axis=None):
    """Sequence-parallel sibling of make_gqa_attn_fn for long-context
    prefill (parallel/sequence.py): the chunk's tokens are sharded over
    the mesh's ``sp_axis``; QKV projections / RoPE / MLP are position-
    local and partition for free, attention runs as one ring pass over
    the chunk's fresh K/V merged with the committed paged prefix (read
    in place by the Pallas page-walk kernel, or gathered on the XLA
    fallback — parallel/sequence.sp_chunk_attention), and the
    fresh K/V scatter into the paged cache exactly as the dense path
    does (GSPMD collects the sequence shards at the scatter). B is 1 by
    construction — one oversized prompt owns the whole mesh."""
    from ..parallel.sequence import sp_chunk_attention

    h_heads, hd = cfg.num_heads, cfg.head_dim

    def attn_fn(x, layer_params, k_all, v_all, li):
        q, k, v = qkv_prologue(cfg, x, layer_params, b, s, positions,
                               context_lens)
        # the prefix gather reads the INCOMING cache (pre-scatter): the
        # chunk's own positions are masked there anyway, and gathering
        # before the scatter lets XLA overlap the two instead of
        # serializing on the donated buffer
        attn = sp_chunk_attention(
            q, k, v, k_all, v_all, block_tables, chunk_start,
            context_lens[0], li, mesh, axis=sp_axis, head_axis=head_axis,
            impl=cfg.attention_impl,
        )
        k_all, v_all = scatter_kv_stacked(k_all, v_all, k, v, slot_mapping, li)
        delta = dense(attn.reshape(b, s, h_heads * hd), layer_params["wo"])
        return delta, k_all, v_all

    return attn_fn


def sp_decoder_forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,        # [1, S] one chunk, S sharded over sp
    positions: jax.Array,     # [1, S] absolute positions (pad → repeat last)
    kv_cache: KVCache,
    block_tables: jax.Array,  # [1, W]
    slot_mapping: jax.Array,  # [1, S] flat cache slot per token; -1 drops
    context_lens: jax.Array,  # [1] valid tokens incl. this chunk
    chunk_start,              # traced scalar: chunk's first absolute position
    mesh,
    sp_axis: str = "sp",
    head_axis=None,
    mlp_fn=_swiglu_mlp,
) -> Tuple[jax.Array, KVCache]:
    """One sequence-parallel prefill chunk through the GQA trunk.

    Returns (pre-final-norm hidden [1, S, D], updated kv_cache) — the
    engine samples from the last valid position via logits_from_hidden,
    exactly like the dense step program's return_hidden path."""
    b, s = tokens.shape
    hidden = params["embed"][tokens]
    attn_fn = make_sp_gqa_attn_fn(
        cfg, b, s, positions, slot_mapping, block_tables, context_lens,
        chunk_start, mesh, sp_axis=sp_axis, head_axis=head_axis,
    )
    hidden, kv_cache, _ = run_layers(
        hidden, kv_cache, params["layers"], cfg, attn_fn, mlp_fn
    )
    return hidden, kv_cache


def run_layers(
    hidden: jax.Array,
    kv_cache: KVCache,
    layers,                   # stacked layer pytree (leading L axis)
    cfg: ModelConfig,
    attn_fn,                  # (x, lp, k_all, v_all, li) -> (delta, k_all, v_all)
    mlp_fn,                   # (x, lp) -> [B, S, D]
    li0: int = 0,             # first layer's index into the KV cache
):
    """One lax.scan over a stacked group of decoder layers.

    Families mix groups with different weights (DeepSeek: k dense layers
    then MoE layers) by chaining calls — ``li0`` keeps cache layer indices
    contiguous across groups. Returns (hidden, kv_cache, next_li).
    """
    k_all, v_all = kv_cache

    def layer_step(carry, layer_params):
        hidden, k_all, v_all, li = carry
        x = rms_norm(hidden, layer_params["ln1"], cfg.rms_norm_eps)
        delta, k_all, v_all = attn_fn(x, layer_params, k_all, v_all, li)
        hidden = hidden + delta
        x = rms_norm(hidden, layer_params["ln2"], cfg.rms_norm_eps)
        hidden = hidden + mlp_fn(x, layer_params)
        return (hidden, k_all, v_all, li + 1), None

    (hidden, k_all, v_all, li), _ = jax.lax.scan(
        layer_step, (hidden, k_all, v_all, jnp.int32(li0)), layers
    )
    return hidden, (k_all, v_all), li


def lm_logits(hidden: jax.Array, params: Params, cfg: ModelConfig) -> jax.Array:
    hidden = rms_norm(hidden, params["final_norm"], cfg.rms_norm_eps)
    lm_head = params.get("lm_head")
    if lm_head is None:
        return hidden @ params["embed"].T  # tied: embed stays unquantized
    return dense(hidden, lm_head)


# the engine's name for "final norm + lm head over any [..., D] slice":
# it samples from last-position hidden states without paying the full
# [B, S, V] head (engine/model_runner.py)
logits_from_hidden = lm_logits


def decoder_forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,        # [B, S]
    positions: jax.Array,     # [B, S] absolute positions (pad → repeat last)
    kv_cache: KVCache,
    block_tables: jax.Array,  # [B, W] (W = kv_width blocks)
    slot_mapping: jax.Array,  # [B, S] flat cache slot per token; -1 drops
    context_lens: jax.Array,  # [B] valid tokens incl. the ones being written
    mesh=None,                # multi-device mesh for the pallas shard_map path
    mlp_fn=_swiglu_mlp,       # (normed_x [B,S,D], layer_params) -> [B,S,D]
    return_hidden: bool = False,
) -> Tuple[jax.Array, KVCache]:
    """Shared decoder trunk: embed → scan(attention + mlp_fn) → logits.

    The attention block (RoPE, paged-KV scatter, GQA attention) is common
    to GQA families; ``mlp_fn`` is the per-family feed-forward — dense
    SwiGLU here, routed experts in models/mixtral.py.
    Returns (logits [B, S, V], updated kv_cache) — or the pre-final-norm
    hidden states [B, S, D] with ``return_hidden``, so the engine can
    run ``logits_from_hidden`` on just the positions it samples (the
    full-S lm head is the dominant prefill matmul otherwise).
    """
    b, s = tokens.shape
    hidden = params["embed"][tokens]  # [B, S, D]
    attn_fn = make_gqa_attn_fn(
        cfg, b, s, positions, slot_mapping, block_tables, context_lens, mesh
    )
    hidden, kv_cache, _ = run_layers(
        hidden, kv_cache, params["layers"], cfg, attn_fn, mlp_fn
    )
    if return_hidden:
        return hidden, kv_cache
    return lm_logits(hidden, params, cfg), kv_cache


def embed_forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,      # [R, S] right-padded prompt rows
    positions: jax.Array,   # [R, S] (pad → repeat last)
    valid_lens: jax.Array,  # [R] real tokens per row
) -> jax.Array:
    """Prefill-only trunk for the embeddings workload: dense causal
    self-attention with NO cache reads or writes (the whole context is
    the prompt; nothing decodes afterwards, so paged-KV state would be
    pure waste), final norm, and the LAST valid position's hidden state
    as the sequence embedding — the standard decoder-LM pooling. The
    engine L2-normalizes at the edge. Returns [R, D] float32."""
    from ..ops.attention import prefill_attention

    b, s = tokens.shape
    h_heads, hd = cfg.num_heads, cfg.head_dim
    hidden = params["embed"][tokens]

    def attn_fn(x, layer_params, k_all, v_all, li):
        q, k, v = qkv_prologue(cfg, x, layer_params, b, s, positions,
                               valid_lens)
        attn = prefill_attention(q, k, v, valid_lens)
        delta = dense(attn.reshape(b, s, h_heads * hd), layer_params["wo"])
        return delta, k_all, v_all

    dummy = jnp.zeros((), jnp.float32)
    hidden, _, _ = run_layers(
        hidden, (dummy, dummy), params["layers"], cfg, attn_fn, _swiglu_mlp
    )
    hidden = rms_norm(hidden, params["final_norm"], cfg.rms_norm_eps)
    rows = jnp.arange(b)
    return hidden[rows, valid_lens - 1].astype(jnp.float32)


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,
    positions: jax.Array,
    kv_cache: KVCache,
    block_tables: jax.Array,
    slot_mapping: jax.Array,
    context_lens: jax.Array,
    mesh=None,
    return_hidden: bool = False,
) -> Tuple[jax.Array, KVCache]:
    """Llama forward = shared trunk with the dense SwiGLU MLP."""
    return decoder_forward(
        params, cfg, tokens, positions, kv_cache, block_tables,
        slot_mapping, context_lens, mesh=mesh, mlp_fn=_swiglu_mlp,
        return_hidden=return_hidden,
    )
