"""Weight-only int8 quantization for serving.

Decode is HBM-bandwidth bound: every step streams all weights. Storing
matmul weights as int8 with a per-output-channel scale halves that
stream (and the weights' HBM footprint) for ~2x the decode roofline;
XLA fuses the int8→bf16 convert into the dot's operand read, so no
dequantized copy is ever materialized. Reference analog: the quantized
checkpoints its engines serve as the canonical benchmark workload
(examples/llm/benchmarks/perf.sh:18-54 — an FP8-dynamic model); here
quantization is a serving-time transform (``--quantization int8``)
applied to any loaded checkpoint, bf16 or FP8-upconverted.

Design: ``QuantizedWeight`` is a registered pytree node, so it slices
per layer through the model's ``lax.scan`` over stacked [L, in, out]
weights, shards through ``jax.tree.map`` against a mirrored spec tree,
and donates like any other leaf. Models call ``dense(x, w)`` instead of
``x @ w``; for plain arrays it is exactly ``x @ w``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass
class QuantizedWeight:
    """int8 weight + per-output-channel scale.

    Stacked form: q [L, in, out], scale [L, out]; inside a layer scan
    each slice is q [in, out], scale [out].
    """

    q: Any          # int8
    scale: Any      # f32, |w| max per out column / 127


jax.tree_util.register_dataclass(
    QuantizedWeight, data_fields=["q", "scale"], meta_fields=[]
)


def quantize_int8(w: jax.Array) -> QuantizedWeight:
    """Per-output-channel symmetric int8: scale over the in (second-to-
    last) axis."""
    a = jnp.asarray(w, jnp.float32)
    scale = jnp.max(jnp.abs(a), axis=-2) / 127.0
    scale = jnp.maximum(scale, 1e-8)  # all-zero columns
    q = jnp.clip(jnp.round(a / scale[..., None, :]), -127, 127).astype(jnp.int8)
    return QuantizedWeight(q=q, scale=scale)


def dense(x: jax.Array, w) -> jax.Array:
    """``x @ w`` for plain or quantized weights. The int8 operand is
    converted in-read (XLA fuses convert into the dot); the scale lands
    on the [*, out] result, staying in x's dtype."""
    if isinstance(w, QuantizedWeight):
        return (x @ w.q.astype(x.dtype)) * w.scale.astype(x.dtype)
    return x @ w


def expert_einsum(subscripts: str, x: jax.Array, w) -> jax.Array:
    """Expert-batched matmul for plain or quantized stacked weights.

    Contract (the MoE expert shapes of models/mixtral.py): ``w`` is
    [E, in, out] with the contraction over ``in`` (axis -2), and the
    result is [E, C, out] — so the per-output-channel scale [E, out]
    broadcasts as ``scale[:, None, :]``.
    """
    if isinstance(w, QuantizedWeight):
        y = jnp.einsum(subscripts, x, w.q.astype(x.dtype))
        return y * w.scale.astype(x.dtype)[:, None, :]
    return jnp.einsum(subscripts, x, w)


# weights worth quantizing: the big matmul operands. embed stays full (it
# is a gather + tied-logit transpose), norms and biases are tiny, MoE
# routers steer expert selection (precision-sensitive and tiny), and
# MLA's w_kr/w_uk/w_uv stay full (w_kr keeps RoPE keys exact; w_uk/w_uv
# use nonstandard contraction layouts and are latent-rank small).
LLAMA_QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"}
)
# + DeepSeek shared experts and MLA low-rank projections; mixtral expert
# stacks reuse the w_gate/w_up/w_down names (rank-4 [L, E, in, out] —
# quantize_int8 and the specs are rank-generic)
QUANT_KEYS = LLAMA_QUANT_KEYS | frozenset(
    {"w_sh_gate", "w_sh_up", "w_sh_down", "w_dq", "w_uq", "w_dkv",
     # GPT-OSS fused interleaved gate/up expert stacks: per-out-channel
     # scales are interleaving-safe (each output column owns its scale)
     "w_gate_up"}
)


def quantize_params(params: Dict, keys: frozenset = QUANT_KEYS) -> Dict:
    """Quantize the named matmul weights anywhere in a nested param dict."""
    def walk(node):
        if isinstance(node, dict):
            return {
                k: quantize_int8(v)
                if k in keys and not isinstance(v, QuantizedWeight)
                else walk(v)
                for k, v in node.items()
            }
        return node

    return walk(params)


def mirror_specs(params: Dict, specs: Dict) -> Dict:
    """Rewrite a PartitionSpec tree so quantized leaves get a matching
    QuantizedWeight of specs: q keeps the weight's spec; scale drops the
    in axis (second-to-last entry)."""
    def walk(p, s):
        if isinstance(p, QuantizedWeight):
            if isinstance(s, QuantizedWeight):
                return s  # already mirrored (e.g. built by a tree.map)
            # pad a rank-deficient spec with None (JAX semantics: trailing
            # dims unsharded) so the in/out axes align positionally
            spec = tuple(s) + (None,) * (p.q.ndim - len(tuple(s)))
            if len(spec) != p.q.ndim:
                raise ValueError(
                    f"spec {s} has more entries than the {p.q.ndim}-d weight"
                )
            scale_spec = P(*(spec[:-2] + spec[-1:]))
            return QuantizedWeight(q=P(*spec), scale=scale_spec)
        if isinstance(p, dict):
            return {k: walk(v, s[k]) for k, v in p.items()}
        return s

    return walk(params, specs)
