"""Resolve a model reference (local dir or HF hub id) to a local snapshot.

Reference analog: launch/dynamo-run/src/hub.rs — the reference accepts
either a filesystem path or a HuggingFace repo id everywhere a model is
named and downloads the snapshot on demand. Same contract here: local
paths win; otherwise ``huggingface_hub`` fetches (or reuses its cache —
``HF_HUB_OFFLINE=1`` serves cache-only, the right mode for air-gapped
TPU pods).
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

# weights + tokenizer + metadata; skip consolidated/original torch bins
_SNAPSHOT_PATTERNS = [
    "*.safetensors", "*.json", "*.model", "*.txt", "*.jinja",
]


def resolve_model_path(name_or_path: str, revision: str | None = None) -> str:
    """Local directory or file (.gguf) → itself; else → HF snapshot download.

    Raises a clear error (rather than a deep stack) when the id is not a
    directory and the hub is unreachable and the cache has no copy.
    """
    if os.path.isdir(name_or_path) or (
        os.path.isfile(name_or_path) and name_or_path.endswith(".gguf")
    ):
        return name_or_path
    try:
        from huggingface_hub import snapshot_download
    except ImportError as e:  # pragma: no cover - hub ships in the image
        raise FileNotFoundError(
            f"{name_or_path!r} is not a local directory and huggingface_hub "
            "is unavailable to fetch it"
        ) from e
    try:
        path = snapshot_download(
            name_or_path, revision=revision, allow_patterns=_SNAPSHOT_PATTERNS
        )
        logger.info("resolved %s -> %s", name_or_path, path)
        return path
    except Exception as e:
        raise FileNotFoundError(
            f"cannot resolve model {name_or_path!r}: not a local directory, "
            f"and hub fetch failed ({type(e).__name__}: {e}). For air-gapped "
            "hosts pre-populate the HF cache and set HF_HUB_OFFLINE=1."
        ) from e
