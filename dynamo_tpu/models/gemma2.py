"""Gemma-2 family: sandwich norms, GeGLU, softcapped + alternating
sliding-window attention, tied embeddings.

Architecture deltas vs the llama trunk (matching HF
transformers/models/gemma2/modeling_gemma2.py, validated logit-exact in
tests/test_gemma2.py):

- embeddings scaled by sqrt(hidden_size) (cast to the activation dtype
  first, like HF's ``normalizer`` tensor);
- RMSNorm multiplies by ``1 + weight`` and runs in float32;
- four norms per layer: pre/post attention and pre/post MLP — the post
  norms apply to the block OUTPUT before the residual add;
- GeGLU MLP (tanh-approximated gelu on the gate);
- attention scaled by ``query_pre_attn_scalar**-0.5`` with logit
  softcapping, and EVEN layers see only a sliding window of the cache
  (``config.layer_types``: sliding/full alternating from layer 0);
- logits through the tied embedding with final softcapping.

Softcap/window ride the XLA attention path (ops/attention.py falls back
from Pallas for these semantics). Reference analog: the Gemma models of
the engines the reference delegates to (vLLM model zoo, SURVEY §2.4).
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..engine.config import ModelConfig
from ..ops.attention import attention, scatter_kv_stacked
from .llama import apply_rope, init_kv_cache  # noqa: F401  (shared cache layout)
from .quant import dense

Params = Dict
KVCache = Tuple[jax.Array, jax.Array]

CACHE_SPEC = P(None, None, None, "tp", None)


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    """Gemma RMSNorm: float32 compute, multiply by (1 + weight)."""
    x32 = x.astype(jnp.float32)
    n = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (n * (1.0 + weight.astype(jnp.float32))).astype(x.dtype)


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    l, d_model = cfg.num_layers, cfg.hidden_size
    h, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    inter = cfg.intermediate_size
    keys = jax.random.split(key, 9)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    layers = {
        "ln1": jnp.zeros((l, d_model), dtype),           # (1 + w) centered
        "wq": w(keys[1], (l, d_model, h * hd), d_model),
        "wk": w(keys[2], (l, d_model, kvh * hd), d_model),
        "wv": w(keys[3], (l, d_model, kvh * hd), d_model),
        "wo": w(keys[4], (l, h * hd, d_model), h * hd),
        "ln_post_attn": jnp.zeros((l, d_model), dtype),
        "ln_pre_mlp": jnp.zeros((l, d_model), dtype),
        "w_gate": w(keys[5], (l, d_model, inter), d_model),
        "w_up": w(keys[6], (l, d_model, inter), d_model),
        "w_down": w(keys[7], (l, inter, d_model), inter),
        "ln_post_mlp": jnp.zeros((l, d_model), dtype),
    }
    return {
        "embed": w(keys[0], (cfg.vocab_size, d_model), d_model),
        "layers": layers,
        "final_norm": jnp.zeros((d_model,), dtype),
    }


def param_specs(params: Params) -> Dict:
    layer_specs = {
        "ln1": P(), "ln_post_attn": P(), "ln_pre_mlp": P(),
        "ln_post_mlp": P(),
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "w_gate": P(None, None, "tp"),
        "w_up": P(None, None, "tp"),
        "w_down": P(None, "tp", None),
    }
    specs = {
        "embed": P(),
        "final_norm": P(),
        "layers": {k: layer_specs[k] for k in params["layers"]},
    }
    if "lm_head" in params:
        specs["lm_head"] = P(None, "tp")
    return specs


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,        # [B, S]
    positions: jax.Array,     # [B, S]
    kv_cache: KVCache,        # stacked [L, N, bs, KVH, Dpad]
    block_tables: jax.Array,  # [B, W]
    slot_mapping: jax.Array,  # [B, S]
    context_lens: jax.Array,  # [B]
    mesh=None,
    return_hidden: bool = False,
) -> Tuple[jax.Array, KVCache]:
    b, s = tokens.shape
    h, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    eps = cfg.rms_norm_eps
    hidden = params["embed"][tokens]
    hidden = hidden * jnp.asarray(
        math.sqrt(cfg.hidden_size), hidden.dtype
    )
    scale = (cfg.query_pre_attn_scalar or hd) ** -0.5
    k_all, v_all = kv_cache

    def layer_step(carry, lp):
        hidden, k_all, v_all, li = carry
        x = rms_norm(hidden, lp["ln1"], eps)
        q = dense(x, lp["wq"]).reshape(b, s, h, hd)
        k = dense(x, lp["wk"]).reshape(b, s, kvh, hd)
        v = dense(x, lp["wv"]).reshape(b, s, kvh, hd)
        q = apply_rope(q, positions, cfg.rope_theta, None)
        k = apply_rope(k, positions, cfg.rope_theta, None)
        k_all, v_all = scatter_kv_stacked(k_all, v_all, k, v, slot_mapping, li)
        # layer_types alternates sliding/full starting sliding at layer 0
        window = (
            jnp.where(li % 2 == 0, cfg.sliding_window, jnp.int32(1 << 30))
            if cfg.sliding_window else None
        )
        attn = attention(
            q, k_all, v_all, block_tables, positions, context_lens,
            impl=cfg.attention_impl, mesh=mesh, layer_idx=li,
            scale=scale, softcap=cfg.attn_logit_softcap,
            sliding_window=window,
        )
        delta = dense(attn.reshape(b, s, h * hd), lp["wo"])
        hidden = hidden + rms_norm(delta, lp["ln_post_attn"], eps)
        x = rms_norm(hidden, lp["ln_pre_mlp"], eps)
        gate = jax.nn.gelu(dense(x, lp["w_gate"]), approximate=True)
        mlp = dense(gate * dense(x, lp["w_up"]), lp["w_down"])
        hidden = hidden + rms_norm(mlp, lp["ln_post_mlp"], eps)
        return (hidden, k_all, v_all, li + 1), None

    (hidden, k_all, v_all, _), _ = jax.lax.scan(
        layer_step, (hidden, k_all, v_all, jnp.int32(0)), params["layers"]
    )
    if return_hidden:
        return hidden, (k_all, v_all)
    return logits_from_hidden(hidden, params, cfg), (k_all, v_all)


def logits_from_hidden(hidden: jax.Array, params: Params,
                       cfg: ModelConfig) -> jax.Array:
    """Final (1+w) norm + tied-or-untied head + final softcapping over
    any [..., D] slice (the engine samples from last-position hidden)."""
    hidden = rms_norm(hidden, params["final_norm"], cfg.rms_norm_eps)
    lm_head = params.get("lm_head")  # untied finetunes; normally tied
    logits = (
        hidden @ params["embed"].T if lm_head is None
        else dense(hidden, lm_head)
    )
    cap = cfg.final_logit_softcap
    if cap:
        logits = cap * jnp.tanh(logits / cap)
    return logits
