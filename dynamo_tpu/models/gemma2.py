"""Gemma-2 family: sandwich norms, GeGLU, softcapped + alternating
sliding-window attention, tied embeddings.

Architecture deltas vs the llama trunk (matching HF
transformers/models/gemma2/modeling_gemma2.py, validated logit-exact in
tests/test_gemma2.py):

- embeddings scaled by sqrt(hidden_size) (cast to the activation dtype
  first, like HF's ``normalizer`` tensor);
- RMSNorm multiplies by ``1 + weight`` and runs in float32;
- four norms per layer: pre/post attention and pre/post MLP — the post
  norms apply to the block OUTPUT before the residual add;
- GeGLU MLP (tanh-approximated gelu on the gate);
- attention scaled by ``query_pre_attn_scalar**-0.5`` with logit
  softcapping, and EVEN layers see only a sliding window of the cache
  (``config.layer_types``: sliding/full alternating from layer 0);
- logits through the tied embedding with final softcapping.

Softcap/window serve on the Pallas kernels natively (the window rides as
a runtime scalar operand; ops/attention.py). Reference analog: the Gemma
models of the engines the reference delegates to (vLLM model zoo,
SURVEY §2.4).
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..engine.config import ModelConfig
from ..ops.attention import attention, scatter_kv_stacked
from .llama import (  # noqa: F401  (shared cache layout)
    alternating_window,
    apply_rope,
    gather_kv_writes,
    init_kv_cache,
)
from .quant import dense

Params = Dict
KVCache = Tuple[jax.Array, jax.Array]

CACHE_SPEC = P(None, None, None, "tp", None)


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    """Gemma RMSNorm: float32 compute, multiply by (1 + weight)."""
    x32 = x.astype(jnp.float32)
    n = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (n * (1.0 + weight.astype(jnp.float32))).astype(x.dtype)


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    l, d_model = cfg.num_layers, cfg.hidden_size
    h, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    inter = cfg.intermediate_size
    keys = jax.random.split(key, 9)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    layers = {
        "ln1": jnp.zeros((l, d_model), dtype),           # (1 + w) centered
        "wq": w(keys[1], (l, d_model, h * hd), d_model),
        "wk": w(keys[2], (l, d_model, kvh * hd), d_model),
        "wv": w(keys[3], (l, d_model, kvh * hd), d_model),
        "wo": w(keys[4], (l, h * hd, d_model), h * hd),
        "ln_post_attn": jnp.zeros((l, d_model), dtype),
        "ln_pre_mlp": jnp.zeros((l, d_model), dtype),
        "w_gate": w(keys[5], (l, d_model, inter), d_model),
        "w_up": w(keys[6], (l, d_model, inter), d_model),
        "w_down": w(keys[7], (l, inter, d_model), inter),
        "ln_post_mlp": jnp.zeros((l, d_model), dtype),
    }
    return {
        "embed": w(keys[0], (cfg.vocab_size, d_model), d_model),
        "layers": layers,
        "final_norm": jnp.zeros((d_model,), dtype),
    }


def param_specs(params: Params) -> Dict:
    layer_specs = {
        "ln1": P(), "ln_post_attn": P(), "ln_pre_mlp": P(),
        "ln_post_mlp": P(),
        "wq": P(None, None, "tp"),
        "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"),
        "wo": P(None, "tp", None),
        "w_gate": P(None, None, "tp"),
        "w_up": P(None, None, "tp"),
        "w_down": P(None, "tp", None),
    }
    specs = {
        "embed": P(),
        "final_norm": P(),
        "layers": {k: layer_specs[k] for k in params["layers"]},
    }
    if "lm_head" in params:
        specs["lm_head"] = P(None, "tp")
    return specs


def embed_tokens(params: Params, tokens: jax.Array) -> jax.Array:
    """Gemma scales embeddings by sqrt(hidden_size) (HF ``normalizer``)."""
    hidden = params["embed"][tokens]
    d_model = params["embed"].shape[-1]
    return hidden * jnp.asarray(math.sqrt(d_model), hidden.dtype)


def make_attn_fn(cfg, b, s, positions, slot_mapping, block_tables,
                 context_lens, mesh, kv_gather_axis=None, layer_offset=0,
                 tp_axis=None):
    """Gemma-2 attention block for run_layers: plain-rope QKV,
    query_pre_attn_scalar scaling, logit softcap, and the alternating
    per-layer sliding window (EVEN layers windowed). Same contract as
    llama.make_gqa_attn_fn incl. ``kv_gather_axis`` (the pipelined
    pp x dp program's replicated-cache sync; see llama.py).

    ``layer_offset``: under pipeline staging ``li`` is the STAGE-LOCAL
    layer index (it addresses the stage's cache slab), but the
    sliding/full alternation follows the GLOBAL layer number — the
    stage's first global layer index comes in here (may be traced)."""
    del tp_axis  # bias-free projections; the wo matmul is the partial
    h, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    scale = (cfg.query_pre_attn_scalar or hd) ** -0.5

    def attn_fn(x, lp, k_all, v_all, li):
        q = dense(x, lp["wq"]).reshape(b, s, h, hd)
        k = dense(x, lp["wk"]).reshape(b, s, kvh, hd)
        v = dense(x, lp["wv"]).reshape(b, s, kvh, hd)
        q = apply_rope(q, positions, cfg.rope_theta, None)
        k = apply_rope(k, positions, cfg.rope_theta, None)
        if kv_gather_axis is not None:
            k_w, v_w, slots_w = gather_kv_writes(k, v, slot_mapping,
                                                 kv_gather_axis)
        else:
            k_w, v_w, slots_w = k, v, slot_mapping
        k_all, v_all = scatter_kv_stacked(k_all, v_all, k_w, v_w, slots_w, li)
        # layer_types alternates sliding/full starting sliding at layer 0
        window = alternating_window(cfg, li, layer_offset)
        attn = attention(
            q, k_all, v_all, block_tables, positions, context_lens,
            impl=cfg.attention_impl, mesh=mesh, layer_idx=li,
            scale=scale, softcap=cfg.attn_logit_softcap,
            sliding_window=window,
        )
        delta = dense(attn.reshape(b, s, h * hd), lp["wo"])
        return delta, k_all, v_all

    return attn_fn


def mlp_fn(x: jax.Array, lp) -> jax.Array:
    """GeGLU (tanh-approximated gelu on the gate)."""
    gate = jax.nn.gelu(dense(x, lp["w_gate"]), approximate=True)
    return dense(gate * dense(x, lp["w_up"]), lp["w_down"])


def run_layers(hidden, kv_cache, layers, cfg, attn_fn, mlp, li0: int = 0):
    """Sandwich-norm layer scan: pre/post norms around BOTH the attention
    and MLP blocks, post norms applied to the block output before the
    residual add. Same contract as llama.run_layers (pipeline staging
    calls this with psum-wrapped attn/mlp)."""
    eps = cfg.rms_norm_eps
    k_all, v_all = kv_cache

    def layer_step(carry, lp):
        hidden, k_all, v_all, li = carry
        x = rms_norm(hidden, lp["ln1"], eps)
        delta, k_all, v_all = attn_fn(x, lp, k_all, v_all, li)
        hidden = hidden + rms_norm(delta, lp["ln_post_attn"], eps)
        x = rms_norm(hidden, lp["ln_pre_mlp"], eps)
        hidden = hidden + rms_norm(mlp(x, lp), lp["ln_post_mlp"], eps)
        return (hidden, k_all, v_all, li + 1), None

    (hidden, k_all, v_all, li), _ = jax.lax.scan(
        layer_step, (hidden, k_all, v_all, jnp.int32(li0)), layers
    )
    return hidden, (k_all, v_all), li


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,        # [B, S]
    positions: jax.Array,     # [B, S]
    kv_cache: KVCache,        # stacked [L, N, bs, KVH, Dpad]
    block_tables: jax.Array,  # [B, W]
    slot_mapping: jax.Array,  # [B, S]
    context_lens: jax.Array,  # [B]
    mesh=None,
    return_hidden: bool = False,
) -> Tuple[jax.Array, KVCache]:
    b, s = tokens.shape
    hidden = embed_tokens(params, tokens)
    attn_fn = make_attn_fn(
        cfg, b, s, positions, slot_mapping, block_tables, context_lens, mesh
    )
    hidden, kv_cache, _ = run_layers(
        hidden, kv_cache, params["layers"], cfg, attn_fn, mlp_fn
    )
    if return_hidden:
        return hidden, kv_cache
    return logits_from_hidden(hidden, params, cfg), kv_cache


def logits_from_hidden(hidden: jax.Array, params: Params,
                       cfg: ModelConfig) -> jax.Array:
    """Final (1+w) norm + tied-or-untied head + final softcapping over
    any [..., D] slice (the engine samples from last-position hidden)."""
    hidden = rms_norm(hidden, params["final_norm"], cfg.rms_norm_eps)
    lm_head = params.get("lm_head")  # untied finetunes; normally tied
    logits = (
        hidden @ params["embed"].T if lm_head is None
        else dense(hidden, lm_head)
    )
    cap = cfg.final_logit_softcap
    if cap:
        logits = cap * jnp.tanh(logits / cap)
    return logits
