"""Load HF checkpoint weights into the engine's stacked-layer param layout.

HF stores one tensor per layer per projection ([out, in] torch layout);
the engine wants [L, in, out] stacks for lax.scan. Streams tensors from
safetensors shards without loading the whole checkpoint at once.
"""

from __future__ import annotations

import glob
import json
import logging
import os
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..engine.config import ModelConfig

logger = logging.getLogger(__name__)


_SCALE_SUFFIXES = ("_scale", "_scale_inv")


def _dequant_fp8(arr: np.ndarray, scale: Optional[np.ndarray],
                 inverse_blocks: bool) -> np.ndarray:
    """FP8 tensor (as float32) × its scale → float32.

    Two schemes cover the FP8 checkpoints in the wild:
    - ``weight_scale`` (compressed-tensors / FP8-dynamic exports, the
      reference's canonical 70B model examples/llm/benchmarks/perf.sh:18):
      scalar or per-output-channel; straight multiply.
    - ``weight_scale_inv`` (DeepSeek-V3/R1 native FP8): per 128×128
      block; expand blockwise over both weight axes.
    """
    if scale is None:
        return arr
    scale = scale.astype(np.float32)
    if inverse_blocks and scale.ndim == 2 and arr.ndim == 2:
        # fixed 128x128 blocks, last block partial (the layout DeepSeek's
        # quantization_config.weight_block_size=[128,128] describes)
        bs_ = 128
        expanded = np.repeat(np.repeat(scale, bs_, axis=0), bs_, axis=1)
        return arr * expanded[: arr.shape[0], : arr.shape[1]]
    if scale.ndim == 1 and arr.ndim >= 2 and scale.size == arr.shape[0]:
        scale = scale.reshape(-1, *([1] * (arr.ndim - 1)))
    return arr * scale


def _iter_safetensors(model_dir: str):
    """Stream (name, np.ndarray) from all shards. Goes through the torch
    framework because safetensors' numpy framework cannot represent
    bfloat16 (the dtype real Llama-class checkpoints ship in); bf16 stays
    2 bytes/element via an ml_dtypes view so staging a large checkpoint
    doesn't double host RAM.

    FP8 tensors (compressed-tensors ``weight_scale`` exports and
    DeepSeek-native ``weight_scale_inv`` block scales) are upconverted to
    bf16 at load — TPUs have no fp8 compute path in this engine yet, so
    the checkpoint serves at bf16 memory cost (one loud warning)."""
    import ml_dtypes
    import torch
    from safetensors import safe_open

    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no .safetensors under {model_dir}")
    # name → shard, built lazily on the FIRST fp8 tensor (so an fp8
    # weight can find its scale across shard boundaries) — the common
    # bf16/fp16 checkpoint never pays the extra key-listing pass
    index: Dict[str, str] = {}

    def ensure_index() -> Dict[str, str]:
        if not index:
            for p in files:
                with safe_open(p, framework="pt") as f:
                    for n in f.keys():
                        index[n] = p
        return index

    def read(name: str) -> "torch.Tensor":
        with safe_open(index[name], framework="pt") as f:
            return f.get_tensor(name)

    warned = False
    for path in files:
        with safe_open(path, framework="pt") as f:
            for name in f.keys():
                if name.endswith(_SCALE_SUFFIXES) or name.endswith(
                    ("input_scale", "k_scale", "v_scale")
                ):
                    continue  # consumed with (or irrelevant to) a weight
                t = f.get_tensor(name)
                if t.dtype == torch.bfloat16:
                    arr = t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
                elif "float8" in str(t.dtype):
                    if not warned:
                        warned = True
                        logger.warning(
                            "FP8 checkpoint: upconverting to bf16 at load "
                            "(weights occupy 2x the quantized size in HBM; "
                            "TPU-native int8/fp8 compute not yet wired)"
                        )
                    scale = inv = None
                    idx = ensure_index()
                    if f"{name}_scale" in idx:
                        scale = read(f"{name}_scale").to(torch.float32).numpy()
                    elif f"{name}_scale_inv" in idx:
                        inv = read(f"{name}_scale_inv").to(torch.float32).numpy()
                    arr = _dequant_fp8(
                        t.to(torch.float32).numpy(),
                        scale if scale is not None else inv,
                        inverse_blocks=inv is not None,
                    ).astype(ml_dtypes.bfloat16)
                else:
                    arr = t.numpy()
                yield name, arr


def _stream_hf_params(model_dir: str, mapping: Dict, n_layers: int,
                      required, label: str):
    """Shared HF-checkpoint streaming for dense trunks: route the
    top-level tensors (embed / final norm / lm_head, transposed) and
    stage per-layer tensors by ``mapping`` (name → (key, transpose)).
    Validates the ``required`` layer keys are complete; keys outside
    ``required`` (e.g. Qwen's optional qkv biases) must be complete only
    if the checkpoint ships any of them. Returns (top, staging)."""
    staging: Dict[str, Dict[int, np.ndarray]] = {}
    top: Dict[str, np.ndarray] = {}
    for name, tensor in _iter_safetensors(model_dir):
        name = name.removeprefix("model.")
        if name == "embed_tokens.weight":
            top["embed"] = tensor
        elif name == "norm.weight":
            top["final_norm"] = tensor
        elif name == "lm_head.weight":
            top["lm_head"] = tensor.T  # [V, D] → [D, V]
        elif name.startswith("layers."):
            _, idx, rest = name.split(".", 2)
            if rest in mapping:
                key, transpose = mapping[rest]
                staging.setdefault(key, {})[int(idx)] = (
                    tensor.T if transpose else tensor
                )
            else:
                logger.debug("skipping unmapped tensor %s", name)
    present = set(staging) | set(required)
    missing = [k for k in present if len(staging.get(k, ())) != n_layers]
    if missing:
        raise ValueError(
            f"incomplete checkpoint: {label} {missing} have "
            f"{[len(staging.get(k, ())) for k in missing]} of {n_layers} layers"
        )
    return top, staging


def load_llama_params(model_dir: str, cfg: ModelConfig, dtype=jnp.bfloat16) -> Dict:
    """HF Llama/Mistral/Qwen-style checkpoint → stacked param pytree."""
    l = cfg.num_layers
    mapping = {
        "input_layernorm.weight": ("ln1", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "post_attention_layernorm.weight": ("ln2", False),
        "mlp.gate_proj.weight": ("w_gate", True),
        "mlp.up_proj.weight": ("w_up", True),
        "mlp.down_proj.weight": ("w_down", True),
        # Qwen2-family qkv biases (models/llama.py adds them pre-rope);
        # optional — present only when the checkpoint ships them
        "self_attn.q_proj.bias": ("bq", False),
        "self_attn.k_proj.bias": ("bk", False),
        "self_attn.v_proj.bias": ("bv", False),
        # Qwen3-family per-head q/k norms (pre-rope, over head_dim)
        "self_attn.q_norm.weight": ("q_norm", False),
        "self_attn.k_norm.weight": ("k_norm", False),
        # Phi-3 fuses qkv and gate|up into single projections; split
        # below after streaming
        "self_attn.qkv_proj.weight": ("_qkv", True),
        "mlp.gate_up_proj.weight": ("_gate_up", True),
    }
    top, staging = _stream_hf_params(
        model_dir, mapping, l, required=("ln1", "ln2", "wo", "w_down"),
        label="llama",
    )
    if "_qkv" in staging:
        # Phi-3 layout: rows [q | k | v] on the out axis (post-transpose
        # the out axis is last): q = heads*hd, k = v = kv_heads*hd
        qd = cfg.num_heads * cfg.head_dim
        kvd = cfg.num_kv_heads * cfg.head_dim
        for i, t in staging.pop("_qkv").items():
            if t.shape[1] != qd + 2 * kvd:
                # a silent short slice would serve plausible garbage
                raise ValueError(
                    f"fused qkv width {t.shape[1]} != heads*hd + 2*kv*hd "
                    f"= {qd + 2 * kvd} (config/checkpoint mismatch)"
                )
            staging.setdefault("wq", {})[i] = t[:, :qd]
            staging.setdefault("wk", {})[i] = t[:, qd:qd + kvd]
            staging.setdefault("wv", {})[i] = t[:, qd + kvd:]
    if "_gate_up" in staging:
        inter = cfg.intermediate_size
        for i, t in staging.pop("_gate_up").items():
            if t.shape[1] != 2 * inter:
                raise ValueError(
                    f"fused gate_up width {t.shape[1]} != "
                    f"2*intermediate_size = {2 * inter}"
                )
            staging.setdefault("w_gate", {})[i] = t[:, :inter]
            staging.setdefault("w_up", {})[i] = t[:, inter:]
    missing = [k for k in ("wq", "wk", "wv", "w_gate", "w_up")
               if len(staging.get(k, ())) != l]
    if missing:
        raise ValueError(
            f"incomplete checkpoint: llama {missing} incomplete over {l} layers"
        )

    def stack(key):
        return jnp.asarray(
            np.stack([staging[key][i] for i in range(l)]), dtype=dtype
        )

    params = {
        "embed": jnp.asarray(top["embed"], dtype=dtype),
        "layers": {k: stack(k) for k in staging},
        "final_norm": jnp.asarray(top["final_norm"], dtype=dtype),
    }
    if "lm_head" in top:
        params["lm_head"] = jnp.asarray(top["lm_head"], dtype=dtype)
    elif not cfg.tie_word_embeddings:
        # tied but config didn't say so — fall back to tied
        logger.info("no lm_head tensor; using tied embeddings")
    return params


def load_gemma2_params(model_dir: str, cfg: ModelConfig, dtype=jnp.bfloat16) -> Dict:
    """HF Gemma2ForCausalLM checkpoint → stacked param pytree.

    Gemma-2 ships four norms per layer and normally ties lm_head to the
    embedding; an untied finetune's lm_head is honored when present
    (models/gemma2.py applies the (1+w) norm semantics and the
    sqrt(hidden) embedding scale at forward time)."""
    l = cfg.num_layers
    mapping = {
        "input_layernorm.weight": ("ln1", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "post_attention_layernorm.weight": ("ln_post_attn", False),
        "pre_feedforward_layernorm.weight": ("ln_pre_mlp", False),
        "mlp.gate_proj.weight": ("w_gate", True),
        "mlp.up_proj.weight": ("w_up", True),
        "mlp.down_proj.weight": ("w_down", True),
        "post_feedforward_layernorm.weight": ("ln_post_mlp", False),
    }
    top, staging = _stream_hf_params(
        model_dir, mapping, l,
        required=tuple(key for key, _ in mapping.values()), label="gemma2",
    )
    params = {
        "embed": jnp.asarray(top["embed"], dtype=dtype),
        "layers": _stack_group(staging, l, 1, dtype, "gemma2"),
        "final_norm": jnp.asarray(top["final_norm"], dtype=dtype),
    }
    if "lm_head" in top:
        params["lm_head"] = jnp.asarray(top["lm_head"], dtype=dtype)
    return params


def _stack_group(
    staging: Dict[str, Dict], n_layers: int, n_experts: int, dtype, label: str
) -> Dict:
    """Stack a staged layer group into [L, ...] (or [L, E, ...] for keys
    indexed by (layer, expert) tuples), validating completeness."""
    out = {}
    for key, by_idx in staging.items():
        if not by_idx:
            raise ValueError(
                f"incomplete checkpoint: {label}.{key} has 0 tensors"
            )
        per_expert = isinstance(next(iter(by_idx)), tuple)
        want = n_layers * n_experts if per_expert else n_layers
        if len(by_idx) != want:
            raise ValueError(
                f"incomplete checkpoint: {label}.{key} has "
                f"{len(by_idx)}/{want} tensors"
            )
        if per_expert:
            arr = np.stack([
                np.stack([by_idx[(i, j)] for j in range(n_experts)])
                for i in range(n_layers)
            ])
        else:
            arr = np.stack([by_idx[i] for i in range(n_layers)])
        out[key] = jnp.asarray(arr, dtype=dtype)
    return out


def load_mixtral_params(model_dir: str, cfg: ModelConfig, dtype=jnp.bfloat16) -> Dict:
    """HF GShard-MoE checkpoint → stacked param pytree.

    Speaks both tensor naming schemes that resolve to the mixtral
    module: Mixtral's ``block_sparse_moe.{gate,experts.N.w1/w2/w3}`` and
    Qwen3-MoE's ``mlp.{gate,experts.N.gate/up/down_proj}`` (+ Qwen3's
    per-head q/k norms). HF stores one tensor per (layer, expert)
    projection; the engine wants [L, E, in, out] stacks so the
    routed-experts einsums (models/mixtral.py moe_mlp) see every expert
    as one MXU-shaped batched matmul. Reference analog: the reference
    loads MoE checkpoints through its GPU engines' HF loaders
    (launch/dynamo-run/src/lib.rs:131).
    """
    l, e = cfg.num_layers, cfg.num_experts
    staging: Dict[str, Dict] = {}
    top: Dict[str, np.ndarray] = {}

    attn_map = {
        "input_layernorm.weight": ("ln1", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "self_attn.q_norm.weight": ("q_norm", False),
        "self_attn.k_norm.weight": ("k_norm", False),
        "post_attention_layernorm.weight": ("ln2", False),
        "block_sparse_moe.gate.weight": ("router", True),
        "mlp.gate.weight": ("router", True),
    }
    expert_map = {
        "w1": "w_gate", "w2": "w_down", "w3": "w_up",            # mixtral
        "gate_proj": "w_gate", "down_proj": "w_down", "up_proj": "w_up",
    }

    for name, tensor in _iter_safetensors(model_dir):
        name = name.removeprefix("model.")
        if name == "embed_tokens.weight":
            top["embed"] = tensor
        elif name == "norm.weight":
            top["final_norm"] = tensor
        elif name == "lm_head.weight":
            top["lm_head"] = tensor.T
        elif name.startswith("layers."):
            _, idx, rest = name.split(".", 2)
            idx = int(idx)
            if rest in attn_map:
                key, transpose = attn_map[rest]
                staging.setdefault(key, {})[idx] = (
                    tensor.T if transpose else tensor
                )
            elif rest.startswith(("block_sparse_moe.experts.",
                                  "mlp.experts.")):
                _, _, ei, proj, _ = rest.split(".")
                staging.setdefault(expert_map[proj], {})[(idx, int(ei))] = tensor.T
            elif rest.startswith("mlp.shared_expert"):
                # Qwen2-MoE's gated shared expert — distinct semantics
                # (sigmoid-gated output) this module does not implement
                raise NotImplementedError(
                    "Qwen2-MoE shared-expert checkpoints are not "
                    "supported (gated shared expert); Qwen3-MoE and "
                    "Mixtral load"
                )
            else:
                logger.debug("skipping unmapped tensor %s", name)

    layers = _stack_group(staging, l, e, dtype, "layers")
    params = {
        "embed": jnp.asarray(top["embed"], dtype=dtype),
        "layers": layers,
        "final_norm": jnp.asarray(top["final_norm"], dtype=dtype),
    }
    if "lm_head" in top:
        params["lm_head"] = jnp.asarray(top["lm_head"], dtype=dtype)
    return params


# MXFP4 (the canonical GPT-OSS release format): 4-bit e2m1 values packed
# two-per-byte in 16-byte groups of 32, with one e8m0 exponent (biased
# 127) per group
_FP4_VALUES = np.array(
    [0.0, 0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 6.0,
     -0.0, -0.5, -1.0, -1.5, -2.0, -3.0, -4.0, -6.0], np.float32,
)


def _dequant_mxfp4(blocks: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """[..., G, 16] uint8 blocks + [..., G] uint8 exponents →
    [..., G*32] float32 (low nibble first, matching transformers'
    integrations/mxfp4.convert_moe_packed_tensors)."""
    vals = np.empty(blocks.shape[:-1] + (32,), np.float32)
    vals[..., 0::2] = _FP4_VALUES[blocks & 0x0F]
    vals[..., 1::2] = _FP4_VALUES[blocks >> 4]
    vals *= np.exp2(scales.astype(np.int32) - 127)[..., None].astype(np.float32)
    return vals.reshape(blocks.shape[:-2] + (blocks.shape[-2] * 32,))


def load_gptoss_params(model_dir: str, cfg: ModelConfig, dtype=jnp.bfloat16) -> Dict:
    """HF GPT-OSS checkpoint → param pytree (models/gptoss.py layout).

    Unlike Mixtral/Qwen-MoE, the expert projections arrive already
    STACKED per layer (``mlp.experts.gate_up_proj`` [E, D, 2I] etc. —
    one tensor per layer, not per expert), so only the layer axis needs
    stacking. Attention projections transpose like every HF linear; the
    per-head ``sinks`` and all biases load as-is. The canonical MXFP4
    releases (expert tensors shipped as ``*_blocks`` + ``*_scales``)
    dequantize at load — values arrive [E, out, in] and transpose into
    the engine's [E, in, out] stacks.
    """
    l = cfg.num_layers
    staging: Dict[str, Dict] = {}
    mx_staging: Dict[str, Dict] = {}  # (key, kind) -> {layer: tensor}
    top: Dict[str, np.ndarray] = {}

    name_map = {
        "input_layernorm.weight": ("ln1", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.q_proj.bias": ("bq", False),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.k_proj.bias": ("bk", False),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.v_proj.bias": ("bv", False),
        "self_attn.o_proj.weight": ("wo", True),
        "self_attn.o_proj.bias": ("bo", False),
        "self_attn.sinks": ("sinks", False),
        "post_attention_layernorm.weight": ("ln2", False),
        "mlp.router.weight": ("router", True),
        "mlp.router.bias": ("router_bias", False),
        "mlp.experts.gate_up_proj": ("w_gate_up", False),
        "mlp.experts.gate_up_proj_bias": ("b_gate_up", False),
        "mlp.experts.down_proj": ("w_down", False),
        "mlp.experts.down_proj_bias": ("b_down", False),
    }

    for name, tensor in _iter_safetensors(model_dir):
        name = name.removeprefix("model.")
        if name == "embed_tokens.weight":
            top["embed"] = tensor
        elif name == "norm.weight":
            top["final_norm"] = tensor
        elif name == "lm_head.weight":
            top["lm_head"] = tensor.T
        elif name.startswith("layers."):
            _, idx, rest = name.split(".", 2)
            if rest in name_map:
                key, transpose = name_map[rest]
                staging.setdefault(key, {})[int(idx)] = (
                    tensor.T if transpose else tensor
                )
            elif rest.startswith("mlp.experts.") and rest.endswith(
                ("_blocks", "_scales")
            ):
                proj, kind = rest.removeprefix("mlp.experts.").rsplit("_", 1)
                key = {"gate_up_proj": "w_gate_up", "down_proj": "w_down"}[proj]
                mx_staging.setdefault((key, kind), {})[int(idx)] = tensor
            else:
                logger.debug("skipping unmapped tensor %s", name)

    for key in ("w_gate_up", "w_down"):
        blocks = mx_staging.get((key, "blocks"), {})
        scales = mx_staging.get((key, "scales"), {})
        for idx, blk in blocks.items():
            if idx not in scales:
                raise ValueError(
                    f"incomplete MXFP4 checkpoint: layers.{key} layer "
                    f"{idx} has blocks but no scales"
                )
            # dequant [E, out, in] → engine stack [E, in, out]
            staging.setdefault(key, {})[idx] = _dequant_mxfp4(
                blk, scales[idx]
            ).transpose(0, 2, 1)

    layers = _stack_group(staging, l, 0, dtype, "layers")
    required = {key for key, _ in name_map.values()} | {"w_gate_up", "w_down"}
    missing = required - set(layers)
    if missing:
        # _stack_group can only validate keys that matched ≥1 tensor; a
        # wholly-absent group (renamed/unknown format) must still fail
        # with the loader's diagnostic, not a KeyError mid-trace
        raise ValueError(
            f"incomplete checkpoint: layers missing {sorted(missing)} "
            f"(unrecognized tensor naming or quantization format?)"
        )
    params = {
        "embed": jnp.asarray(top["embed"], dtype=dtype),
        "layers": layers,
        "final_norm": jnp.asarray(top["final_norm"], dtype=dtype),
    }
    if "lm_head" in top:
        params["lm_head"] = jnp.asarray(top["lm_head"], dtype=dtype)
    return params


def _rope_deinterleave(n: int) -> np.ndarray:
    """Permutation mapping HF DeepSeek's interleaved rope pairs
    (x[2j], x[2j+1]) to this repo's half-rotation layout (x[j], x[j+n/2]).

    Folding it into the projection weights makes models/llama.apply_rope
    numerically exact vs. HF's complex-multiply rope (the permutation is
    applied to BOTH q_rope and k_rope, so their dot product is invariant).
    """
    return np.concatenate([np.arange(0, n, 2), np.arange(1, n, 2)])


def load_deepseek_params(model_dir: str, cfg: ModelConfig, dtype=jnp.bfloat16) -> Dict:
    """HF DeepSeek-V2/V3 MLA (+ optional MoE) checkpoint → param pytree.

    Layout transforms, all checked against transformers'
    modeling_deepseek_v2.py semantics:
    - ``kv_a_proj_with_mqa`` [r+rope, D] splits into ``w_dkv`` [D, r] and the
      shared rope key projection ``w_kr`` [D, rope];
    - ``kv_b_proj`` [H*(nope+v), r] splits per head into the absorbed
      up-projections ``w_uk`` [r, H, nope] / ``w_uv`` [r, H, v];
    - rope columns of the q projection and ``w_kr`` are de-interleaved
      (see _rope_deinterleave);
    - MoE layers restack at ``idx - first_k_dense_replace``; V3's
      ``e_score_correction_bias`` loads as ``router_bias``.
    """
    nope, rope = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    r, h, vd = cfg.kv_lora_rank, cfg.num_heads, cfg.v_head_dim
    n_dense = min(cfg.first_k_dense_replace, cfg.num_layers) if cfg.num_experts else cfg.num_layers
    n_moe = cfg.num_layers - n_dense
    e = cfg.num_experts
    perm = _rope_deinterleave(rope)

    # staging[group][key][layer-or-(layer,expert)] where group is
    # "dense_layers" (first k) or "layers" (MoE tail)
    staging: Dict[str, Dict[str, Dict]] = {"dense_layers": {}, "layers": {}}
    top: Dict[str, np.ndarray] = {}

    def put(group: str, key: str, idx, value) -> None:
        staging[group].setdefault(key, {})[idx] = value

    def q_deinterleave(t: np.ndarray) -> np.ndarray:
        # t: [in, H*(nope+rope)] — permute each head's rope columns
        t = t.reshape(t.shape[0], h, nope + rope).copy()
        t[..., nope:] = t[..., nope + perm]
        return t.reshape(t.shape[0], -1)

    for name, tensor in _iter_safetensors(model_dir):
        name = name.removeprefix("model.")
        if name == "embed_tokens.weight":
            top["embed"] = tensor
            continue
        if name == "norm.weight":
            top["final_norm"] = tensor
            continue
        if name == "lm_head.weight":
            top["lm_head"] = tensor.T
            continue
        if not name.startswith("layers."):
            continue
        _, idx, rest = name.split(".", 2)
        idx = int(idx)
        group = "dense_layers" if idx < n_dense else "layers"
        li = idx if idx < n_dense else idx - n_dense

        if rest == "input_layernorm.weight":
            put(group, "ln1", li, tensor)
        elif rest == "post_attention_layernorm.weight":
            put(group, "ln2", li, tensor)
        elif rest == "self_attn.q_proj.weight":
            put(group, "wq", li, q_deinterleave(tensor.T))
        elif rest == "self_attn.q_a_proj.weight":
            put(group, "w_dq", li, tensor.T)
        elif rest == "self_attn.q_a_layernorm.weight":
            put(group, "ln_q", li, tensor)
        elif rest == "self_attn.q_b_proj.weight":
            put(group, "w_uq", li, q_deinterleave(tensor.T))
        elif rest == "self_attn.kv_a_proj_with_mqa.weight":
            t = tensor.T  # [D, r+rope]
            put(group, "w_dkv", li, t[:, :r])
            put(group, "w_kr", li, t[:, r:][:, perm])
        elif rest == "self_attn.kv_a_layernorm.weight":
            put(group, "ln_kv", li, tensor)
        elif rest == "self_attn.kv_b_proj.weight":
            t = tensor.reshape(h, nope + vd, r)  # [H, nope+v, r]
            put(group, "w_uk", li, np.transpose(t[:, :nope, :], (2, 0, 1)))
            put(group, "w_uv", li, np.transpose(t[:, nope:, :], (2, 0, 1)))
        elif rest == "self_attn.o_proj.weight":
            put(group, "wo", li, tensor.T)
        elif rest.startswith("mlp.experts."):
            _, _, ei, proj, _ = rest.split(".")
            key = {"gate_proj": "w_gate", "up_proj": "w_up", "down_proj": "w_down"}[proj]
            put(group, key, (li, int(ei)), tensor.T)
        elif rest.startswith("mlp.shared_experts."):
            _, _, proj, _ = rest.split(".")
            key = {
                "gate_proj": "w_sh_gate", "up_proj": "w_sh_up",
                "down_proj": "w_sh_down",
            }[proj]
            put(group, key, li, tensor.T)
        elif rest == "mlp.gate.weight":
            put(group, "router", li, tensor.T)
        elif rest == "mlp.gate.e_score_correction_bias":
            put(group, "router_bias", li, tensor)
        elif rest in (
            "mlp.gate_proj.weight", "mlp.up_proj.weight", "mlp.down_proj.weight"
        ):
            key = {
                "mlp.gate_proj.weight": "w_gate",
                "mlp.up_proj.weight": "w_up",
                "mlp.down_proj.weight": "w_down",
            }[rest]
            put(group, key, li, tensor.T)
        else:
            logger.debug("skipping unmapped tensor %s", name)

    params: Dict = {
        "embed": jnp.asarray(top["embed"], dtype=dtype),
        "final_norm": jnp.asarray(top["final_norm"], dtype=dtype),
    }
    if "lm_head" in top:
        params["lm_head"] = jnp.asarray(top["lm_head"], dtype=dtype)
    if n_dense > 0:
        params["dense_layers"] = _stack_group(
            staging["dense_layers"], n_dense, 0, dtype, "dense_layers"
        )
    if n_moe > 0:
        params["layers"] = _stack_group(staging["layers"], n_moe, e, dtype, "layers")
    return params


def _gguf_unpermute(w: np.ndarray, n_head: int) -> np.ndarray:
    """Invert llama.cpp's q/k row permutation on a [out, in] weight.

    The public HF→GGUF converter permutes attn_q/attn_k rows so ggml's
    interleaved rope matches HF's half-rotation rope
    (w.reshape(H, 2, out//H//2, in).swapaxes(1, 2)); this engine uses the
    HF convention (models/llama.apply_rope), so loading a .gguf must undo
    it per head.
    """
    out, inner = w.shape
    hd = out // n_head
    return (
        w.reshape(n_head, hd // 2, 2, inner)
        .swapaxes(1, 2)
        .reshape(out, inner)
    )


def load_gguf_llama_params(path: str, cfg: ModelConfig, dtype=jnp.bfloat16) -> Dict:
    """llama.cpp ``.gguf`` checkpoint → stacked param pytree.

    Tensor data dequantizes through llm/gguf_tensors.py (f16/bf16 and the
    common q* block formats); names follow llama.cpp's export scheme
    (token_embd, blk.N.attn_q, ...). With this the engine serves a .gguf
    end-to-end: tokenizer from metadata (llm/gguf.py), weights from here.
    """
    import ml_dtypes

    from ..llm.gguf import read_gguf
    from ..llm.gguf_tensors import iter_gguf_tensors

    # dequantization yields float32; staging a whole 70B checkpoint at 4
    # bytes per element would need ~4x the serving footprint in host RAM,
    # so narrow to the target dtype per tensor as it streams in
    stage_dtype = (
        ml_dtypes.bfloat16 if dtype == jnp.bfloat16
        else np.float16 if dtype == jnp.float16
        else np.float32
    )

    l = cfg.num_layers
    staging: Dict[str, Dict[int, np.ndarray]] = {
        k: {} for k in ("ln1", "wq", "wk", "wv", "wo", "ln2", "w_gate", "w_up", "w_down")
    }
    top: Dict[str, np.ndarray] = {}
    mapping = {
        "attn_norm.weight": ("ln1", False),
        "attn_q.weight": ("wq", True),
        "attn_k.weight": ("wk", True),
        "attn_v.weight": ("wv", True),
        "attn_output.weight": ("wo", True),
        "ffn_norm.weight": ("ln2", False),
        "ffn_gate.weight": ("w_gate", True),
        "ffn_up.weight": ("w_up", True),
        "ffn_down.weight": ("w_down", True),
    }

    g = read_gguf(path)
    for name, tensor in iter_gguf_tensors(path, g):
        tensor = tensor.astype(stage_dtype)
        if name == "token_embd.weight":
            top["embed"] = tensor
        elif name == "output_norm.weight":
            top["final_norm"] = tensor
        elif name == "output.weight":
            top["lm_head"] = tensor.T
        elif name.startswith("blk."):
            _, idx, rest = name.split(".", 2)
            if rest not in mapping:
                logger.debug("skipping unmapped gguf tensor %s", name)
                continue
            key, transpose = mapping[rest]
            if key == "wq":
                tensor = _gguf_unpermute(tensor, cfg.num_heads)
            elif key == "wk":
                tensor = _gguf_unpermute(tensor, cfg.num_kv_heads)
            staging[key][int(idx)] = tensor.T if transpose else tensor

    missing = [k for k, v in staging.items() if len(v) != l]
    if missing:
        raise ValueError(
            f"incomplete gguf checkpoint: {missing} have "
            f"{[len(staging[k]) for k in missing]} of {l} layers"
        )

    params = {
        "embed": jnp.asarray(top["embed"], dtype=dtype),
        "layers": {
            k: jnp.asarray(
                np.stack([staging[k][i] for i in range(l)]), dtype=dtype
            )
            for k in staging
        },
        "final_norm": jnp.asarray(top["final_norm"], dtype=dtype),
    }
    if "lm_head" in top:
        params["lm_head"] = jnp.asarray(top["lm_head"], dtype=dtype)
    elif not cfg.tie_word_embeddings:
        logger.info("no output.weight in gguf; using tied embeddings")
    return params


def load_checkpoint_params(model_dir: str, cfg: ModelConfig, arch, dtype=jnp.bfloat16) -> Dict:
    """Dispatch to the loader for the resolved architecture module.

    ``model_dir`` may be an HF snapshot directory or a ``.gguf`` file.
    Raises (rather than silently serving random weights — a user pointing
    the engine at a real checkpoint must never get plausible-looking
    garbage) when no loader exists for the architecture.
    """
    name = arch.__name__.rsplit(".", 1)[-1]
    if model_dir.endswith(".gguf"):
        if name != "llama":
            raise NotImplementedError(
                f"gguf loading is llama-family only (got {name!r})"
            )
        return load_gguf_llama_params(model_dir, cfg, dtype)
    loaders = {
        "llama": load_llama_params,
        "mixtral": load_mixtral_params,
        "deepseek": load_deepseek_params,
        "gemma2": load_gemma2_params,
        "gptoss": load_gptoss_params,
    }
    if name not in loaders:
        raise NotImplementedError(
            f"no weight loader for architecture {name!r} (checkpoint at {model_dir})"
        )
    return loaders[name](model_dir, cfg, dtype)


def has_checkpoint(model_dir: str) -> bool:
    if model_dir.endswith(".gguf"):
        return os.path.exists(model_dir)
    return bool(glob.glob(os.path.join(model_dir, "*.safetensors")))
