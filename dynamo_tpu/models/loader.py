"""Load HF checkpoint weights into the engine's stacked-layer param layout.

HF stores one tensor per layer per projection ([out, in] torch layout);
the engine wants [L, in, out] stacks for lax.scan. Streams tensors from
safetensors shards without loading the whole checkpoint at once.
"""

from __future__ import annotations

import glob
import json
import logging
import os
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..engine.config import ModelConfig

logger = logging.getLogger(__name__)


def _iter_safetensors(model_dir: str):
    from safetensors import safe_open

    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no .safetensors under {model_dir}")
    for path in files:
        with safe_open(path, framework="np") as f:
            for name in f.keys():
                yield name, f.get_tensor(name)


def load_llama_params(model_dir: str, cfg: ModelConfig, dtype=jnp.bfloat16) -> Dict:
    """HF Llama/Mistral/Qwen-style checkpoint → stacked param pytree."""
    l = cfg.num_layers
    staging: Dict[str, Dict[int, np.ndarray]] = {
        k: {} for k in ("ln1", "wq", "wk", "wv", "wo", "ln2", "w_gate", "w_up", "w_down")
    }
    top: Dict[str, np.ndarray] = {}

    def to_np(t):
        if t.dtype == np.dtype("uint16"):  # bfloat16 raw view
            import jax

            return jnp.asarray(t.view(jnp.bfloat16))
        return t

    mapping = {
        "input_layernorm.weight": ("ln1", False),
        "self_attn.q_proj.weight": ("wq", True),
        "self_attn.k_proj.weight": ("wk", True),
        "self_attn.v_proj.weight": ("wv", True),
        "self_attn.o_proj.weight": ("wo", True),
        "post_attention_layernorm.weight": ("ln2", False),
        "mlp.gate_proj.weight": ("w_gate", True),
        "mlp.up_proj.weight": ("w_up", True),
        "mlp.down_proj.weight": ("w_down", True),
    }

    for name, tensor in _iter_safetensors(model_dir):
        name = name.removeprefix("model.")
        if name == "embed_tokens.weight":
            top["embed"] = tensor
        elif name == "norm.weight":
            top["final_norm"] = tensor
        elif name == "lm_head.weight":
            top["lm_head"] = tensor.T  # [V, D] → [D, V]
        elif name.startswith("layers."):
            _, idx, rest = name.split(".", 2)
            if rest in mapping:
                key, transpose = mapping[rest]
                staging[key][int(idx)] = tensor.T if transpose else tensor
            else:
                logger.debug("skipping unmapped tensor %s", name)

    missing = [k for k, v in staging.items() if len(v) != l]
    if missing:
        raise ValueError(
            f"incomplete checkpoint: {missing} have "
            f"{[len(staging[k]) for k in missing]} of {l} layers"
        )

    def stack(key):
        return jnp.asarray(
            np.stack([staging[key][i] for i in range(l)]), dtype=dtype
        )

    params = {
        "embed": jnp.asarray(top["embed"], dtype=dtype),
        "layers": {k: stack(k) for k in staging},
        "final_norm": jnp.asarray(top["final_norm"], dtype=dtype),
    }
    if "lm_head" in top:
        params["lm_head"] = jnp.asarray(top["lm_head"], dtype=dtype)
    elif not cfg.tie_word_embeddings:
        # tied but config didn't say so — fall back to tied
        logger.info("no lm_head tensor; using tied embeddings")
    return params


def has_checkpoint(model_dir: str) -> bool:
    return bool(glob.glob(os.path.join(model_dir, "*.safetensors")))
