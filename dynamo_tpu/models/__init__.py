"""Model registry: architecture config → implementing module.

Each model module exposes the same functional surface —
``init_params(cfg, key, dtype)``, ``init_kv_cache(cfg, n, bs, dtype)``,
``forward(params, cfg, ...)`` and ``param_specs(params)`` — so the engine
(engine/model_runner.py) is architecture-agnostic. The reference's
equivalent "model family" axis lived inside its delegated GPU engines
(vLLM/SGLang model zoos, SURVEY.md §2.4); here the zoo is native.
"""

from __future__ import annotations

from ..engine.config import ModelConfig


def resolve(cfg: ModelConfig):
    """Pick the implementing module for an architecture config."""
    if cfg.kv_lora_rank > 0:
        try:
            from . import deepseek
        except ImportError as e:  # pragma: no cover
            raise NotImplementedError(
                "kv_lora_rank > 0 selects MLA attention (DeepSeek-class), "
                "which requires dynamo_tpu/models/deepseek.py"
            ) from e
        return deepseek
    if cfg.model_family == "gptoss":
        from . import gptoss

        return gptoss
    if cfg.num_experts > 0:
        from . import mixtral

        return mixtral
    if cfg.model_family == "gemma2":
        from . import gemma2

        return gemma2
    from . import llama

    return llama
