"""DeepSeek-class decoder with Multi-head Latent Attention (MLA).

MLA compresses each token's KV state to a low-rank latent ``c_kv``
(kv_lora_rank wide) plus one shared RoPE key (qk_rope_head_dim wide) —
the paged cache stores ONLY those two vectors per token, cutting KV
memory by ~an order of magnitude vs per-head K/V and letting far more
sequences fit in HBM (the reference serves DeepSeek-R1 only by delegating
to engines that implement MLA; SURVEY.md §7 step 8 names MLA a scale-out
milestone for this framework).

TPU-first formulation — the *absorbed* form runs everywhere (prefill and
decode) so attention reads the compressed cache directly:

    score(q, t) = (q_nope W_uk) · c_kv[t] + q_rope · k_rope[t]
    out_latent  = softmax(score) @ c_kv        ->  o = out_latent W_uv W_o

i.e. W_uk is folded into the query and W_uv applied after attention, so
the per-token cache line stays [kv_lora_rank + qk_rope_head_dim] and the
big einsums stay MXU-shaped. TP shards query/output heads; the latent
cache is replicated over tp (it is tiny and per-token, not per-head).

Full DeepSeek-V2/V3 MLP topology: the first ``first_k_dense_replace``
layers use a dense SwiGLU at ``intermediate_size``; the remaining layers
are MoE with experts at ``moe_intermediate_size`` plus ``n_shared_experts``
always-on shared experts. All of it reuses the shared trunk pieces:
llama.run_layers scans each layer group, mixtral.make_moe_mlp_fn routes.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..engine.config import ModelConfig
from ..ops.attention import lane_pad, scatter_kv_stacked
from ..ops.compat import shard_map
from .llama import (
    _swiglu_mlp,
    apply_rope,
    base_specs,
    gather_kv_writes,
    lm_logits,
    rms_norm,
    run_layers,
)
from .mixtral import make_moe_mlp_fn
from .quant import dense

Params = Dict[str, Any]
KVCache = Tuple[jax.Array, jax.Array]  # (latent c_kv, shared k_rope) caches

# the latent cache is replicated across tp (no head dim to shard)
CACHE_SPEC = P()



def init_kv_cache(
    cfg: ModelConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16
) -> KVCache:
    """Compressed cache: c_kv [L,N,bs,1,r] + k_rope [L,N,bs,1,rd].

    Minor dims are lane-padded (ops/attention.lane_pad): free in HBM and
    required by the MLA decode kernel's manual page DMA."""
    c = jnp.zeros(
        (cfg.num_layers, num_blocks, block_size, 1, lane_pad(cfg.kv_lora_rank)),
        dtype,
    )
    kr = jnp.zeros(
        (cfg.num_layers, num_blocks, block_size, 1,
         lane_pad(cfg.qk_rope_head_dim)),
        dtype,
    )
    return c, kr


def _split_layer_counts(cfg: ModelConfig) -> Tuple[int, int]:
    """(dense-prefix layers, MoE layers)."""
    if cfg.num_experts <= 0:
        return cfg.num_layers, 0
    k = min(cfg.first_k_dense_replace, cfg.num_layers)
    return k, cfg.num_layers - k


def _attn_params(cfg: ModelConfig, n_layers: int, key, w, dtype) -> Dict:
    d_model, h = cfg.hidden_size, cfg.num_heads
    r, qr = cfg.kv_lora_rank, cfg.q_lora_rank
    nope, rope = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    vd = cfg.v_head_dim
    l = n_layers
    keys = jax.random.split(key, 8)
    out: Dict[str, jax.Array] = {
        "ln1": jnp.ones((l, d_model), dtype),
        "w_dkv": w(keys[0], (l, d_model, r), d_model),
        "ln_kv": jnp.ones((l, r), dtype),
        "w_kr": w(keys[1], (l, d_model, rope), d_model),
        "w_uk": w(keys[2], (l, r, h, nope), r),
        "w_uv": w(keys[3], (l, r, h, vd), r),
        "wo": w(keys[4], (l, h * vd, d_model), h * vd),
        "ln2": jnp.ones((l, d_model), dtype),
    }
    if qr > 0:
        out["w_dq"] = w(keys[5], (l, d_model, qr), d_model)
        out["ln_q"] = jnp.ones((l, qr), dtype)
        out["w_uq"] = w(keys[6], (l, qr, h * (nope + rope)), qr)
    else:
        out["wq"] = w(keys[5], (l, d_model, h * (nope + rope)), d_model)
    return out


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    d_model = cfg.hidden_size
    inter = cfg.intermediate_size
    moe_inter = cfg.moe_intermediate_size or inter
    e = cfg.num_experts
    n_dense, n_moe = _split_layer_counts(cfg)
    keys = jax.random.split(key, 12)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (fan_in ** -0.5)).astype(dtype)

    params: Params = {
        "embed": w(keys[0], (cfg.vocab_size, d_model), d_model),
        "final_norm": jnp.ones((d_model,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(keys[1], (d_model, cfg.vocab_size), d_model)

    if n_dense > 0:
        group = _attn_params(cfg, n_dense, keys[2], w, dtype)
        group["w_gate"] = w(keys[3], (n_dense, d_model, inter), d_model)
        group["w_up"] = w(keys[4], (n_dense, d_model, inter), d_model)
        group["w_down"] = w(keys[5], (n_dense, inter, d_model), inter)
        params["dense_layers"] = group

    if n_moe > 0:
        moe = _attn_params(cfg, n_moe, keys[6], w, dtype)
        moe["router"] = w(keys[7], (n_moe, d_model, e), d_model)
        moe["w_gate"] = w(keys[8], (n_moe, e, d_model, moe_inter), d_model)
        moe["w_up"] = w(keys[9], (n_moe, e, d_model, moe_inter), d_model)
        moe["w_down"] = w(keys[10], (n_moe, e, moe_inter, d_model), moe_inter)
        if cfg.n_shared_experts > 0:
            sh = cfg.n_shared_experts * moe_inter
            sk = jax.random.split(keys[11], 3)
            moe["w_sh_gate"] = w(sk[0], (n_moe, d_model, sh), d_model)
            moe["w_sh_up"] = w(sk[1], (n_moe, d_model, sh), d_model)
            moe["w_sh_down"] = w(sk[2], (n_moe, sh, d_model), sh)
        params["layers"] = moe
    return params


_MLA_ATTN_SPECS = {
    "ln1": P(), "ln2": P(), "ln_kv": P(),
    "w_dkv": P(), "w_kr": P(),
    "w_uk": P(None, None, "tp", None),
    "w_uv": P(None, None, "tp", None),
    "wo": P(None, "tp", None),
    "wq": P(None, None, "tp"),
    "w_dq": P(), "ln_q": P(), "w_uq": P(None, None, "tp"),
}


_DENSE_LAYER_SPECS = {
    **_MLA_ATTN_SPECS,
    "w_gate": P(None, None, "tp"),
    "w_up": P(None, None, "tp"),
    "w_down": P(None, "tp", None),
}
_MOE_LAYER_SPECS = {
    **_MLA_ATTN_SPECS,
    "router": P(),
    "router_bias": P(),
    "w_gate": P(None, "ep", None, "tp"),
    "w_up": P(None, "ep", None, "tp"),
    "w_down": P(None, "ep", "tp", None),
    "w_sh_gate": P(None, None, "tp"),
    "w_sh_up": P(None, None, "tp"),
    "w_sh_down": P(None, "tp", None),
}


def param_specs(params: Params) -> Dict:
    """Heads shard over tp; latent down-projections + cache replicate;
    experts (if MoE) over ep like models/mixtral.py."""
    specs = base_specs(params)
    if "dense_layers" in params:
        specs["dense_layers"] = {
            k: _DENSE_LAYER_SPECS[k] for k in params["dense_layers"]
        }
    if "layers" in params:  # present iff the config is MoE
        specs["layers"] = {k: _MOE_LAYER_SPECS[k] for k in params["layers"]}
    return specs


def mla_paged_attention(
    q_lat: jax.Array,      # [B, S, H, r] — queries absorbed into latent space
    q_rope: jax.Array,     # [B, S, H, rd] — post-RoPE decoupled queries
    c_cache: jax.Array,    # [N, bs, 1, r]
    kr_cache: jax.Array,   # [N, bs, 1, rd]
    block_tables: jax.Array,  # [B, W]
    q_positions: jax.Array,   # [B, S]
    context_lens: jax.Array,  # [B]
    scale: float,
) -> jax.Array:
    """Attention over the compressed cache; returns latent output [B,S,H,r]."""
    b, s, h, r = q_lat.shape
    _, block_size, _, rd = kr_cache.shape
    w = block_tables.shape[1]
    t = w * block_size

    # upcast from the cache storage dtype (fp8 serving stores e4m3)
    c = c_cache[block_tables].reshape(b, t, r).astype(q_lat.dtype)
    kr = kr_cache[block_tables].reshape(b, t, rd).astype(q_lat.dtype)

    scores = (
        jnp.einsum("bshr,btr->bsht", q_lat, c)
        + jnp.einsum("bshd,btd->bsht", q_rope, kr)
    ) * scale
    key_pos = jnp.arange(t)[None, None, :]
    mask = (key_pos <= q_positions[:, :, None]) & (
        key_pos < context_lens[:, None, None]
    )
    scores = jnp.where(mask[:, :, None, :], scores, jnp.finfo(scores.dtype).min)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q_lat.dtype)
    return jnp.einsum("bsht,btr->bshr", probs, c)


def mla_attention(
    q_lat, q_rope, c_all, kr_all, li, block_tables, positions, context_lens,
    scale, impl="auto", mesh=None, interpret=False,
):
    """MLA attention dispatch over the stacked compressed caches.

    Decode (S == 1) on the Pallas path uses the MLA decode kernel
    (ops/pallas_decode.py), which indexes the layer inside HBM — no
    per-layer gather. Other shapes (and the XLA path) gather the layer
    and run the dense formulation. Query heads shard over "tp" under a
    multi-device mesh; the latent caches are replicated (no head dim).
    """
    from ..ops.attention import _pad_minor, resolve_attention_impl

    # caches carry lane padding; zero-padded queries score 0 against the
    # zero pad lanes, and the padded latent output is sliced back below
    r = q_lat.shape[-1]
    q_lat = _pad_minor(q_lat, c_all.shape[-1])
    q_rope = _pad_minor(q_rope, kr_all.shape[-1])

    if (
        q_lat.shape[1] == 1
        and resolve_attention_impl(impl) == "pallas"
    ):
        from ..ops.pallas_decode import mla_paged_decode_attention

        def fn(ql, qr, c, kr, bt, ctx, li):
            return mla_paged_decode_attention(
                ql, qr, c, kr, bt, ctx, layer_idx=li, scale=scale,
                interpret=interpret,
            )

        li_arr = jnp.asarray(li, jnp.int32)
        if mesh is not None and mesh.size > 1:
            dp = "dp" if q_lat.shape[0] % mesh.shape.get("dp", 1) == 0 else None
            fn = shard_map(
                fn,
                mesh=mesh,
                in_specs=(
                    P(dp, None, "tp", None),   # q_lat [B, 1, H, R]
                    P(dp, None, "tp", None),   # q_rope
                    CACHE_SPEC,                # c cache (replicated)
                    CACHE_SPEC,                # kr cache
                    P(dp, None),               # block_tables
                    P(dp),                     # context_lens
                    P(),                       # layer idx
                ),
                out_specs=P(dp, None, "tp", None),
                check_vma=False,
            )
        return fn(q_lat, q_rope, c_all, kr_all, block_tables,
                  context_lens, li_arr)[..., :r]

    # layer indexing through the gather (see ops/attention.attention):
    # block n of layer li is flat row li*N + n — no full-layer copy
    l, n_blocks = c_all.shape[:2]
    c_flat = c_all.reshape((l * n_blocks,) + c_all.shape[2:])
    kr_flat = kr_all.reshape((l * n_blocks,) + kr_all.shape[2:])
    li_arr = jnp.asarray(li, jnp.int32)
    return mla_paged_attention(
        q_lat, q_rope, c_flat, kr_flat, block_tables + li_arr * n_blocks,
        positions, context_lens, scale,
    )[..., :r]


def mla_softmax_scale(cfg) -> float:
    """MLA attention softmax scale, incl. DeepSeek's yarn mscale.

    With yarn + mscale_all_dim, the softmax scale carries mscale_all_dim²
    over the WHOLE score (nope + rope); the rope part's cos/sin carry the
    mscale/mscale_all ratio (llama.apply_rope) — together the rope score
    scales by mscale², per DeepSeek's own modeling code (the checkpoints
    were trained with it). transformers' NATIVE DeepseekV2 class omits
    the softmax adjustment (its V3 class applies it); this framework
    follows the canonical training-time semantics for both —
    tests/test_loaders.py pins this computed scale.
    """
    from .llama import _yarn_mscale

    scale = (cfg.qk_nope_head_dim + cfg.qk_rope_head_dim) ** -0.5
    sc = cfg.rope_scaling or {}
    if (sc.get("rope_type") or sc.get("type")) == "yarn":
        mscale_all = float(sc.get("mscale_all_dim") or 0.0)
        if mscale_all:
            m = _yarn_mscale(float(sc.get("factor", 1.0)), mscale_all)
            scale = scale * m * m
    return scale


def make_mla_attn_fn(cfg, b, s, positions, slot_mapping, block_tables,
                     context_lens, mesh=None, kv_gather_axis=None):
    """MLA attention block for llama.run_layers.

    ``kv_gather_axis``: inside a manual shard_map whose batch rows shard
    over that axis while the latent cache stays replicated across it
    (the pipelined pp x dp program), every member must apply every
    member's cache writes — the new latent/rope-key rows and their slots
    are all-gathered over the axis before the scatter (exactly
    llama.make_gqa_attn_fn's contract)."""
    h = cfg.num_heads
    nope, rope_d = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim
    scale = mla_softmax_scale(cfg)

    def attn_fn(x, lp, c_all, kr_all, li):
        # queries (optionally through the q low-rank bottleneck);
        # quant.dense serves these int8 under --quantization (w_kr and
        # the absorbed w_uk/w_uv stay full precision, see quant.py keys)
        if "w_uq" in lp:
            cq = rms_norm(dense(x, lp["w_dq"]), lp["ln_q"], cfg.rms_norm_eps)
            qfull = dense(cq, lp["w_uq"]).reshape(b, s, h, nope + rope_d)
        else:
            qfull = dense(x, lp["wq"]).reshape(b, s, h, nope + rope_d)
        q_nope, q_rope = qfull[..., :nope], qfull[..., nope:]
        q_rope = apply_rope(q_rope, positions, cfg.rope_theta, cfg.rope_scaling)

        # compressed KV state for the new tokens
        c_kv = rms_norm(dense(x, lp["w_dkv"]), lp["ln_kv"], cfg.rms_norm_eps)
        kr = apply_rope(
            (x @ lp["w_kr"])[:, :, None, :], positions, cfg.rope_theta,
            cfg.rope_scaling,
        )  # [B, S, 1, rd]

        # in-place scatter into the stacked caches
        c_w, kr_w, slots_w = c_kv[:, :, None, :], kr, slot_mapping
        if kv_gather_axis is not None:
            c_w, kr_w, slots_w = gather_kv_writes(
                c_w, kr_w, slot_mapping, kv_gather_axis
            )
        c_all, kr_all = scatter_kv_stacked(
            c_all, kr_all, c_w, kr_w, slots_w, li
        )

        # absorb W_uk into the query, attend over the latent cache
        q_lat = jnp.einsum("bshn,rhn->bshr", q_nope, lp["w_uk"])
        o_lat = mla_attention(
            q_lat, q_rope, c_all, kr_all, li, block_tables, positions,
            context_lens, scale, impl=cfg.attention_impl, mesh=mesh,
        )
        o = jnp.einsum("bshr,rhv->bshv", o_lat, lp["w_uv"])
        delta = dense(o.reshape(b, s, -1), lp["wo"])
        return delta, c_all, kr_all

    return attn_fn


def make_attn_fn(cfg, b, s, positions, slot_mapping, block_tables,
                 context_lens, mesh=None, kv_gather_axis=None,
                 layer_offset=0, tp_axis=None):
    """Pipeline attention factory (parallel/pipeline.py family-hook
    contract, the pattern Gemma-2/GPT-OSS stage through). MLA has no
    per-layer alternation, so ``layer_offset`` is accepted and ignored;
    ``tp_axis`` must be None — the latent cache has a single head, so
    there is no head axis to shard inside a manual-tp stage (MLA tp runs
    on the GSPMD non-pp path; model_runner guards this)."""
    del layer_offset
    if tp_axis is not None:
        raise NotImplementedError(
            "MLA under pp composes with dp/ep, not manual tp (the "
            "compressed latent cache has no head axis to shard)"
        )
    return make_mla_attn_fn(
        cfg, b, s, positions, slot_mapping, block_tables, context_lens,
        mesh=mesh, kv_gather_axis=kv_gather_axis,
    )


def pp_trunk_specs(group: Dict) -> Dict:
    """Per-leaf tp/ep specs for the ONE homogeneous layer group the
    pipeline stages (parallel/pipeline.py consults this instead of
    param_specs because the staged group may be the renamed
    dense_layers of a non-MoE config)."""
    table = _MOE_LAYER_SPECS if "router" in group else _DENSE_LAYER_SPECS
    return {k: table[k] for k in group}


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,        # [B, S]
    positions: jax.Array,     # [B, S]
    kv_cache: KVCache,
    block_tables: jax.Array,  # [B, W]
    slot_mapping: jax.Array,  # [B, S]
    context_lens: jax.Array,  # [B]
    mesh=None,
    return_hidden: bool = False,
) -> Tuple[jax.Array, KVCache]:
    """Returns (logits [B, S, V], updated (c_kv, k_rope) caches). Dense
    prefix layers then MoE layers, chained through one contiguous cache.

    Decode steps on the Pallas path run the MLA decode kernel
    (ops/pallas_decode.py mla_paged_decode_attention); prefill and the
    XLA path run the dense gather formulation (mla_paged_attention)."""
    b, s = tokens.shape
    hidden = params["embed"][tokens]
    attn_fn = make_mla_attn_fn(
        cfg, b, s, positions, slot_mapping, block_tables, context_lens,
        mesh=mesh,
    )

    li = 0
    if "dense_layers" in params:
        hidden, kv_cache, li = run_layers(
            hidden, kv_cache, params["dense_layers"], cfg, attn_fn,
            _swiglu_mlp, li0=li,
        )
    if "layers" in params:  # present iff the config is MoE
        hidden, kv_cache, li = run_layers(
            hidden, kv_cache, params["layers"], cfg, attn_fn,
            make_moe_mlp_fn(cfg, b, s, slot_mapping), li0=li,
        )
    if return_hidden:
        return hidden, kv_cache
    return lm_logits(hidden, params, cfg), kv_cache


# final norm + lm head over any [..., D] slice (engine/model_runner.py)
logits_from_hidden = lm_logits
