"""GPT-OSS family: MoE trunk with attention sinks, qkv/o biases, and
alternating sliding-window attention.

Architecture deltas vs the llama trunk (matching HF
transformers/models/gpt_oss/modeling_gpt_oss.py, validated logit-exact
in tests/test_gptoss.py):

- every attention projection carries a bias (incl. the output proj);
- a learned per-head attention SINK joins each softmax as a virtual key
  with no value — only the denominator grows (ops/attention.py sinks;
  rides the XLA path);
- EVEN layers see only a sliding window of the cache (config
  layer_types alternates sliding/full from layer 0 — the gemma2
  pattern, enforced at config parse);
- yarn rope at theta 150k;
- routed experts with a clamped sigmoid-GLU, fused interleaved gate_up
  projection, per-projection biases, and a router whose bias
  participates in both selection and combine weights
  (models/mixtral.py gptoss_moe).

Reference analog: the GPT-OSS models of the engines the reference
delegates to (vLLM model zoo, SURVEY §2.4).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..engine.config import ModelConfig
from ..ops.attention import attention, scatter_kv_stacked
from ..ops.compat import axis_size
from .llama import (  # noqa: F401  (shared cache layout + trunk pieces)
    alternating_window,
    apply_rope,
    embed_tokens,
    gather_kv_writes,
    init_kv_cache,
    lm_logits,
    rms_norm,
    run_layers,
)
from .mixtral import expert_capacity, gptoss_moe
from .quant import dense

Params = Dict
KVCache = Tuple[jax.Array, jax.Array]

CACHE_SPEC = P(None, None, None, "tp", None)

logits_from_hidden = lm_logits


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    l, d_model = cfg.num_layers, cfg.hidden_size
    h, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    e, inter = cfg.num_experts, cfg.intermediate_size
    keys = jax.random.split(key, 10)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    params: Params = {
        "embed": w(keys[0], (cfg.vocab_size, d_model), d_model),
        "layers": {
            "ln1": jnp.ones((l, d_model), dtype),
            "wq": w(keys[1], (l, d_model, h * hd), d_model),
            "bq": jnp.zeros((l, h * hd), dtype),
            "wk": w(keys[2], (l, d_model, kvh * hd), d_model),
            "bk": jnp.zeros((l, kvh * hd), dtype),
            "wv": w(keys[3], (l, d_model, kvh * hd), d_model),
            "bv": jnp.zeros((l, kvh * hd), dtype),
            "wo": w(keys[4], (l, h * hd, d_model), h * hd),
            "bo": jnp.zeros((l, d_model), dtype),
            "sinks": jnp.zeros((l, h), dtype),
            "ln2": jnp.ones((l, d_model), dtype),
            "router": w(keys[5], (l, d_model, e), d_model),
            "router_bias": jnp.zeros((l, e), dtype),
            "w_gate_up": w(keys[6], (l, e, d_model, 2 * inter), d_model),
            "b_gate_up": jnp.zeros((l, e, 2 * inter), dtype),
            "w_down": w(keys[7], (l, e, inter, d_model), inter),
            "b_down": jnp.zeros((l, e, d_model), dtype),
        },
        "final_norm": jnp.ones((d_model,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(keys[8], (d_model, cfg.vocab_size), d_model)
    return params


def param_specs(params: Params) -> Dict:
    """Megatron TP on the attention projections; experts over ep with
    their intermediates over tp. The interleaved gate/up layout shards
    cleanly: a contiguous chunk of the 2I columns covers whole gate/up
    pairs whenever I % tp == 0, and those pairs' intermediate channels
    are exactly the w_down row chunk of the same tp member."""
    layer_specs = {
        "ln1": P(), "ln2": P(),
        "wq": P(None, None, "tp"), "bq": P(None, "tp"),
        "wk": P(None, None, "tp"), "bk": P(None, "tp"),
        "wv": P(None, None, "tp"), "bv": P(None, "tp"),
        "wo": P(None, "tp", None), "bo": P(),
        "sinks": P(None, "tp"),
        "router": P(), "router_bias": P(),
        "w_gate_up": P(None, "ep", None, "tp"),
        "b_gate_up": P(None, "ep", "tp"),
        "w_down": P(None, "ep", "tp", None),
        "b_down": P(None, "ep", None),
    }
    specs = {
        "embed": P(),
        "final_norm": P(),
        "layers": {k: layer_specs[k] for k in params["layers"]},
    }
    if "lm_head" in params:
        specs["lm_head"] = P(None, "tp")
    return specs


def make_attn_fn(cfg, b, s, positions, slot_mapping, block_tables,
                 context_lens, mesh, kv_gather_axis=None, layer_offset=0,
                 tp_axis=None):
    """GPT-OSS attention for run_layers: biased QKV/O, yarn rope, the
    per-head sink logits, and the alternating per-layer window (EVEN
    global layers windowed; ``layer_offset`` carries the stage's first
    global layer index under pipeline staging).

    ``tp_axis`` (manual shard_map): the returned delta must be a
    tp-PARTIAL the caller psums — the wo matmul already is (row-sharded
    weights), but the replicated output bias ``bo`` would be counted tp
    times, so it scales by 1/tp here."""
    h, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    def attn_fn(x, lp, k_all, v_all, li):
        q = (dense(x, lp["wq"]) + lp["bq"]).reshape(b, s, h, hd)
        k = (dense(x, lp["wk"]) + lp["bk"]).reshape(b, s, kvh, hd)
        v = (dense(x, lp["wv"]) + lp["bv"]).reshape(b, s, kvh, hd)
        q = apply_rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = apply_rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
        if kv_gather_axis is not None:
            k_w, v_w, slots_w = gather_kv_writes(k, v, slot_mapping,
                                                 kv_gather_axis)
        else:
            k_w, v_w, slots_w = k, v, slot_mapping
        k_all, v_all = scatter_kv_stacked(k_all, v_all, k_w, v_w, slots_w, li)
        window = alternating_window(cfg, li, layer_offset)
        attn = attention(
            q, k_all, v_all, block_tables, positions, context_lens,
            impl=cfg.attention_impl, mesh=mesh, layer_idx=li,
            sliding_window=window, sinks=lp["sinks"],
        )
        bo = lp["bo"]
        if tp_axis is not None:
            bo = bo / axis_size(tp_axis)
        delta = dense(attn.reshape(b, s, h * hd), lp["wo"]) + bo
        return delta, k_all, v_all

    return attn_fn


def make_mlp_fn(cfg: ModelConfig, b: int, s: int, slot_mapping: jax.Array,
                ep_axis=None, tp_axis=None):
    """Routed-experts mlp_fn (gptoss_moe) for run_layers; ``ep_axis`` /
    ``tp_axis`` are the manual-shard_map axes (pipeline staging) — the
    routed output becomes a partial sum the caller reduces. Expert
    biases stay exact under both: each member adds its local experts'
    (ep) and local channels' (b_gate_up under tp) biases only, and the
    output-dim b_down scales by 1/tp inside gptoss_moe."""
    capacity = expert_capacity(
        b * s, cfg.num_experts, cfg.num_experts_per_tok,
        cfg.moe_capacity_factor,
    )
    valid = (slot_mapping.reshape(b * s) >= 0).astype(jnp.float32)

    def mlp(x, lp):
        y = gptoss_moe(
            x.reshape(b * s, -1),
            lp["router"], lp["router_bias"],
            lp["w_gate_up"], lp["b_gate_up"], lp["w_down"], lp["b_down"],
            cfg.num_experts_per_tok, capacity, valid=valid,
            ep_axis=ep_axis, tp_axis=tp_axis,
        )
        return y.reshape(b, s, -1)

    return mlp


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,        # [B, S]
    positions: jax.Array,     # [B, S]
    kv_cache: KVCache,        # stacked [L, N, bs, KVH, Dpad]
    block_tables: jax.Array,  # [B, W]
    slot_mapping: jax.Array,  # [B, S]
    context_lens: jax.Array,  # [B]
    mesh=None,
    return_hidden: bool = False,
) -> Tuple[jax.Array, KVCache]:
    b, s = tokens.shape
    hidden = embed_tokens(params, tokens)
    attn_fn = make_attn_fn(
        cfg, b, s, positions, slot_mapping, block_tables, context_lens, mesh
    )
    hidden, kv_cache, _ = run_layers(
        hidden, kv_cache, params["layers"], cfg, attn_fn,
        make_mlp_fn(cfg, b, s, slot_mapping),
    )
    if return_hidden:
        return hidden, kv_cache
    return logits_from_hidden(hidden, params, cfg), kv_cache
