"""Mixtral-family sparse-MoE decoder with expert parallelism.

Same attention trunk as models/llama.py (GQA + RoPE + paged KV, one
lax.scan over stacked layer weights); the dense SwiGLU MLP is replaced by
a top-k routed mixture of experts.

TPU-first dispatch (GShard/Switch dense formulation, not the reference's
approach — the reference only passes moe_expert_parallel_size through to
TRT-LLM, SURVEY.md §2.12): routing produces a 0/1 dispatch tensor
[T, E, C] (token → expert slot with capacity C), expert compute is three
batched einsums over [E, C, D] — static shapes, MXU-shaped matmuls, no
scatter/gather — and the expert (E) dimension shards over the mesh's
``ep`` axis while expert intermediates shard over ``tp``. XLA inserts the
token all-to-alls implied by resharding [T, E, C] against [E, ...].

Tokens beyond an expert's capacity are dropped for that expert (their
residual stream still flows); capacity_factor sizes C.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from typing import Optional

from ..engine.config import ModelConfig
from ..ops.compat import axis_size
from .llama import (  # shared trunk + specs
    ATTN_LAYER_SPECS,
    base_specs,
    decoder_forward,
    init_kv_cache,
    logits_from_hidden,  # noqa: F401  (engine samples from hidden slices)
)
from .quant import dense, expert_einsum

Params = Dict[str, Any]
KVCache = Tuple[jax.Array, jax.Array]

__all__ = [
    "init_params", "init_kv_cache", "forward", "param_specs", "moe_mlp",
    "make_moe_mlp_fn", "expert_capacity",
]


def expert_capacity(
    num_tokens: int, num_experts: int, top_k: int, capacity_factor: float = 2.0
) -> int:
    """Per-expert slot count C. At factor 1.0 a perfectly balanced router
    drops nothing; headroom absorbs imbalance."""
    return max(1, int(num_tokens * top_k * capacity_factor / num_experts))


def _dispatch_combine(gate_vals, gate_idx, e: int, capacity: int,
                      valid: Optional[jax.Array],
                      ep_axis: Optional[str] = None):
    """Token-major slot assignment shared by every routed-MoE variant:
    one-hot the expert choices, queue tokens per expert with a cumsum,
    drop past ``capacity``, and return the [T, E, C] dispatch (0/1) and
    combine (gate-weighted) tensors. Pad tokens (``valid == 0``) claim
    no slots and contribute nothing.

    ``ep_axis`` (manual shard_map callers): the queueing runs over the
    GLOBAL expert set — capacity order identical to unsharded math —
    and the tensors are then sliced to this member's experts, making
    the caller's output a partial sum to psum over the axis."""
    t, top_k = gate_idx.shape
    onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.float32)      # [T, K, E]
    if valid is not None:
        onehot = onehot * valid[:, None, None]
        gate_vals = gate_vals * valid[:, None]
    flat = onehot.reshape(t * top_k, e)
    pos = jnp.cumsum(flat, axis=0) - flat                        # queue pos
    keep = (pos < capacity).astype(jnp.float32) * flat
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
    slot = (pos_oh * keep[..., None]).reshape(t, top_k, e, capacity)
    dispatch = slot.sum(axis=1)                                  # [T, E, C]
    combine = (slot * gate_vals[:, :, None, None]).sum(axis=1)
    if ep_axis is not None:
        e_local = e // axis_size(ep_axis)
        e0 = lax.axis_index(ep_axis) * e_local
        dispatch = lax.dynamic_slice_in_dim(dispatch, e0, e_local, axis=1)
        combine = lax.dynamic_slice_in_dim(combine, e0, e_local, axis=1)
    return dispatch, combine


def moe_mlp(
    x: jax.Array,         # [T, D] flattened tokens
    router_w: jax.Array,  # [D, E]
    w_gate: jax.Array,    # [E, D, I]
    w_up: jax.Array,      # [E, D, I]
    w_down: jax.Array,    # [E, I, D]
    top_k: int,
    capacity: int,
    valid: Optional[jax.Array] = None,  # [T] 1.0 = real token, 0.0 = pad
    scoring: str = "softmax",           # "softmax" (Mixtral/V2) | "sigmoid" (V3)
    norm_topk: bool = True,             # renormalize top-k gate weights
    routed_scaling: float = 1.0,        # DeepSeek routed_scaling_factor
    router_bias: Optional[jax.Array] = None,  # [E] V3 e_score_correction_bias
    n_group: int = 1,                   # DeepSeek group-limited routing
    topk_group: int = 1,                # groups the top-k may draw from
    ep_axis: Optional[str] = None,      # manual-shard_map expert axis
) -> jax.Array:
    """Top-k routed SwiGLU experts via dense one-hot dispatch.

    Pad tokens (``valid == 0``) claim no expert slots and contribute
    nothing — otherwise bucket padding would displace real tokens from
    capacity-bounded experts. Routing semantics are configurable to match
    the checkpoint family: Mixtral = softmax scores + renormalized top-k;
    DeepSeek-V2 = softmax, norm_topk_prob=False, scaled routed output;
    DeepSeek-V3 = sigmoid scores.

    ``ep_axis``: inside a manual shard_map where the expert stacks are
    sharded over that mesh axis (the pipelined pp x ep program), the
    routing (cheap, replicated) runs over the GLOBAL expert set and the
    dispatch/combine tensors are sliced to this member's experts; the
    returned value is then a PARTIAL sum the caller must psum over the
    axis (together with its tp reduction).
    """
    e = router_w.shape[1]

    logits = (x @ router_w).astype(jnp.float32)                          # [T, E]
    if scoring == "sigmoid":
        probs = jax.nn.sigmoid(logits)
    elif scoring == "softmax":
        probs = jax.nn.softmax(logits, axis=-1)
    else:
        raise ValueError(f"unknown moe scoring {scoring!r}")
    # selection scores vs combine weights: V3's bias steers expert
    # *selection* only; the combine weights are always the unbiased probs
    select = probs if router_bias is None else probs + router_bias[None, :]
    if n_group > 1:
        # DeepSeek group-limited routing (reference serves these configs
        # via vLLM passthrough, lib/engines/vllm0_8/src/lib.rs:374-380):
        # score each group of E/G experts — V3 "noaux_tc" by its top-2
        # sum of biased scores, V2 "group_limited_greedy" by its max —
        # keep the topk_group best groups, and zero every other expert's
        # selection score (HF masked_fill(~mask, 0.0); scores are
        # sigmoid/softmax outputs ≥ 0, so zeroed experts lose top_k to
        # any live one)
        t = select.shape[0]
        gsize = e // n_group
        grouped = select.reshape(t, n_group, gsize)
        if router_bias is not None:
            top2, _ = lax.top_k(grouped, min(2, gsize))
            group_scores = top2.sum(axis=-1)                       # [T, G]
        else:
            group_scores = grouped.max(axis=-1)                    # [T, G]
        _, gsel = lax.top_k(group_scores, topk_group)              # [T, KG]
        gmask = jax.nn.one_hot(gsel, n_group, dtype=select.dtype).sum(1)
        select = jnp.where(
            jnp.repeat(gmask, gsize, axis=1) > 0, select, 0.0
        )
    _, gate_idx = lax.top_k(select, top_k)                         # [T, K]
    gate_vals = jnp.take_along_axis(probs, gate_idx, axis=1)
    if norm_topk:
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(axis=-1, keepdims=True), 1e-9
        )
    gate_vals = gate_vals * routed_scaling

    # e from router_w, not the expert stacks' .shape — they may be
    # QuantizedWeight (int8 serving), which carries no .shape
    dispatch, combine = _dispatch_combine(gate_vals, gate_idx, e, capacity,
                                          valid, ep_axis=ep_axis)

    x_e = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)   # [E, C, D]
    # expert_einsum: dispatches to int8 weights (scale on the out axis)
    # when the checkpoint is served quantized
    h = jax.nn.silu(expert_einsum("ecd,edi->eci", x_e, w_gate))
    h = h * expert_einsum("ecd,edi->eci", x_e, w_up)
    y_e = expert_einsum("eci,eid->ecd", h, w_down)                 # [E, C, D]
    return jnp.einsum("tec,ecd->td", combine.astype(x.dtype), y_e)


def gptoss_moe(
    x: jax.Array,          # [T, D] flattened tokens
    router_w: jax.Array,   # [D, E]
    router_b: jax.Array,   # [E]
    w_gate_up: jax.Array,  # [E, D, 2I] (gate/up INTERLEAVED on the last dim)
    b_gate_up: jax.Array,  # [E, 2I]
    w_down: jax.Array,     # [E, I, D]
    b_down: jax.Array,     # [E, D]
    top_k: int,
    capacity: int,
    valid: Optional[jax.Array] = None,
    alpha: float = 1.702,
    limit: float = 7.0,
    ep_axis: Optional[str] = None,
    tp_axis: Optional[str] = None,
) -> jax.Array:
    """GPT-OSS routed experts (semantics match HF modeling_gpt_oss):

    - router logits include the bias in BOTH selection and combine
      weights, softmaxed over the selected top-k only;
    - experts compute a clamped sigmoid-GLU: gate capped at +limit, up
      clamped to ±limit, out = (up+1) · gate·sigmoid(alpha·gate);
    - gate/up arrive interleaved in one fused projection, and every
      projection carries a bias.
    Same dense one-hot dispatch/capacity machinery as moe_mlp, incl.
    the manual-shard_map ``ep_axis`` contract (partial sums the caller
    psums over the axis).

    ``tp_axis`` (manual shard_map): the expert stacks arrive tp-SHARDED
    — w_gate_up/b_gate_up a contiguous even-aligned chunk of the
    interleaved 2I columns (whole gate/up pairs, matching the w_down row
    chunk of the same intermediate channels), so the local clamped-GLU
    is exact on its channels and the down contraction is a genuine
    tp-partial; b_down (an output-dim bias every member would add)
    scales by 1/tp so the caller's psum restores it once.
    """
    e = router_w.shape[1]

    logits = (x @ router_w).astype(jnp.float32) + router_b.astype(jnp.float32)
    gate_vals, gate_idx = lax.top_k(logits, top_k)                   # [T, K]
    gate_vals = jax.nn.softmax(gate_vals, axis=-1)

    dispatch, combine = _dispatch_combine(gate_vals, gate_idx, e, capacity,
                                          valid, ep_axis=ep_axis)

    x_e = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)     # [E, C, D]
    gu = expert_einsum("ecd,edi->eci", x_e, w_gate_up) + b_gate_up[:, None, :]
    gate = jnp.minimum(gu[..., 0::2], limit)
    up = jnp.clip(gu[..., 1::2], -limit, limit)
    h = (up + 1.0) * (gate * jax.nn.sigmoid(gate * alpha))
    y_e = expert_einsum("eci,eid->ecd", h, w_down)
    b = b_down[:, None, :]
    if tp_axis is not None:
        b = b / axis_size(tp_axis)
    y_e = y_e + b
    return jnp.einsum("tec,ecd->td", combine.astype(x.dtype), y_e)


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    l, d_model = cfg.num_layers, cfg.hidden_size
    h, kvh, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    inter = cfg.moe_intermediate_size or cfg.intermediate_size
    e = cfg.num_experts
    keys = jax.random.split(key, 12)

    def w(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32) * (fan_in ** -0.5)).astype(dtype)

    params: Params = {
        "embed": w(keys[0], (cfg.vocab_size, d_model), d_model),
        "layers": {
            "ln1": jnp.ones((l, d_model), dtype),
            "wq": w(keys[1], (l, d_model, h * hd), d_model),
            "wk": w(keys[2], (l, d_model, kvh * hd), d_model),
            "wv": w(keys[3], (l, d_model, kvh * hd), d_model),
            "wo": w(keys[4], (l, h * hd, d_model), h * hd),
            "ln2": jnp.ones((l, d_model), dtype),
            "router": w(keys[5], (l, d_model, e), d_model),
            "w_gate": w(keys[6], (l, e, d_model, inter), d_model),
            "w_up": w(keys[7], (l, e, d_model, inter), d_model),
            "w_down": w(keys[8], (l, e, inter, d_model), inter),
        },
        "final_norm": jnp.ones((d_model,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = w(keys[9], (d_model, cfg.vocab_size), d_model)
    return params


def param_specs(params: Params) -> Dict:
    """Megatron TP on attention; experts over ep, expert intermediates over
    tp (so one expert's matmuls still tensor-parallelize within its group)."""
    layer_specs = {
        **ATTN_LAYER_SPECS,
        "router": P(),
        "w_gate": P(None, "ep", None, "tp"),
        "w_up": P(None, "ep", None, "tp"),
        "w_down": P(None, "ep", "tp", None),
    }
    specs = base_specs(params)
    specs["layers"] = {k: layer_specs[k] for k in params["layers"]}
    return specs


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,        # [B, S]
    positions: jax.Array,     # [B, S]
    kv_cache: KVCache,
    block_tables: jax.Array,  # [B, W]
    slot_mapping: jax.Array,  # [B, S]
    context_lens: jax.Array,  # [B]
    mesh=None,
    return_hidden: bool = False,
) -> Tuple[jax.Array, KVCache]:
    """Returns (logits [B, S, V], updated kv_cache): the shared decoder
    trunk (models/llama.py decoder_forward) with the routed-experts MLP.
    Bucket-padding tokens (slot_mapping < 0) are masked out of routing."""
    b, s = tokens.shape
    return decoder_forward(
        params, cfg, tokens, positions, kv_cache, block_tables,
        slot_mapping, context_lens, mesh=mesh,
        mlp_fn=make_moe_mlp_fn(cfg, b, s, slot_mapping),
        return_hidden=return_hidden,
    )


def make_moe_mlp_fn(cfg: ModelConfig, b: int, s: int, slot_mapping: jax.Array,
                    ep_axis: Optional[str] = None,
                    tp_axis: Optional[str] = None):
    """Routed-experts mlp_fn for run_layers/decoder_forward; shared with
    models/deepseek.py (DeepSeek MoE layers, incl. its shared expert).
    ``ep_axis`` (manual shard_map callers): see moe_mlp — the routed part
    becomes a partial sum the caller reduces over the axis. ``tp_axis``
    is accepted for factory-contract uniformity and ignored: the
    bias-free expert stacks tp-shard their inner dims, so the output is
    already a genuine tp-partial."""
    del tp_axis
    capacity = expert_capacity(
        b * s, cfg.num_experts, cfg.num_experts_per_tok, cfg.moe_capacity_factor
    )
    valid = (slot_mapping.reshape(b * s) >= 0).astype(jnp.float32)

    def mlp(x, layer_params):
        y = moe_mlp(
            x.reshape(b * s, -1),
            layer_params["router"],
            layer_params["w_gate"], layer_params["w_up"], layer_params["w_down"],
            cfg.num_experts_per_tok, capacity, valid=valid,
            scoring=cfg.moe_scoring_func, norm_topk=cfg.norm_topk_prob,
            routed_scaling=cfg.routed_scaling_factor,
            router_bias=layer_params.get("router_bias"),
            n_group=cfg.n_group, topk_group=cfg.topk_group,
            ep_axis=ep_axis,
        )
        y = y.reshape(b, s, -1)
        if "w_sh_gate" in layer_params:
            # always-on shared expert(s) alongside the routed ones
            gate = jax.nn.silu(dense(x, layer_params["w_sh_gate"]))
            sh = dense(
                gate * dense(x, layer_params["w_sh_up"]),
                layer_params["w_sh_down"],
            )
            if ep_axis is not None:
                # the caller psums the routed PARTIAL over ep (and tp);
                # the shared expert's weights replicate across ep, so
                # every member computes the same contribution — scale by
                # 1/ep so the joint psum restores it exactly once (the
                # same trick gptoss uses for its replicated biases under
                # manual tp). Under tp the w_sh_* columns/rows shard
                # Megatron-style, so sh is already a genuine tp-partial.
                sh = sh / axis_size(ep_axis)
            y = y + sh
        return y

    return mlp
