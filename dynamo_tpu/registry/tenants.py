"""TenantQuotas: per-tenant token-bucket admission at the HTTP edge.

Multi-tenant isolation layered ON TOP of the existing priority-class
admission (planner/admission.py): the ``X-Tenant`` header maps each
request to an admission class with its own token buckets — one in
requests (refilled at ``requests_per_s``) and one in generated/streamed
tokens (refilled at ``tokens_per_s``). A tenant that exceeds its quota
is shed with 429 + Retry-After (``dynamo_planner_admissions_total``
``outcome="quota"``) while every other tenant's requests proceed
untouched — a spike sheds the spiker, not the fleet.

Parsing mirrors the X-Priority contract: an absent, unknown, or garbage
header degrades to the ``default`` tenant (counted on
``dynamo_registry_tenant_fallbacks_total``), never a 500 — quota
enforcement is a service-protection mechanism, not input validation.

The token bucket is charged by ACTUAL streamed tokens (the edge calls
``charge_tokens`` per payload chunk), so the balance may overdraft
below zero; an overdrafted tenant is shed until refill catches up —
bursts are allowed up to ``burst_s`` seconds of rate, then paid back.
"""

from __future__ import annotations

import dataclasses
import json
import math
import re
import time
from typing import Callable, Dict, Optional

from ..planner.admission import AdmissionRejected
from ..telemetry.registry import MetricsRegistry

TENANT_HEADER = "X-Tenant"
DEFAULT_TENANT = "default"
# a usable tenant id; anything else is garbage and degrades to default
_TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


def parse_tenant(header_value: Optional[str]) -> str:
    """Header → tenant id, quota-free: tenant IDENTITY (who is asking,
    for card visibility) is independent of whether quota enforcement is
    configured. Absent or garbage degrades to the default tenant —
    the X-Priority parsing contract."""
    if not header_value:
        return DEFAULT_TENANT
    v = header_value.strip()
    return v if _TENANT_RE.match(v) else DEFAULT_TENANT


@dataclasses.dataclass
class TenantQuota:
    requests_per_s: float = 0.0   # 0 = unlimited
    tokens_per_s: float = 0.0     # 0 = unlimited
    burst_s: float = 2.0          # bucket capacity = rate × burst_s

    @classmethod
    def from_wire(cls, d: dict) -> "TenantQuota":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: float(v) for k, v in d.items() if k in known})


class _Bucket:
    __slots__ = ("rate", "cap", "level", "refill_t")

    def __init__(self, rate: float, burst_s: float, now: float):
        self.rate = rate
        # capacity never below one unit, or a 0.5 rps tenant could
        # never admit anything at all
        self.cap = max(1.0, rate * burst_s)
        self.level = self.cap
        self.refill_t = now

    def refill(self, now: float) -> None:
        if self.rate <= 0 or now <= self.refill_t:
            # a caller's clock sample may predate the bucket's creation
            # by a tick — never refill backwards
            return
        self.level = min(self.cap,
                         self.level + (now - self.refill_t) * self.rate)
        self.refill_t = now

    def until(self, target: float) -> float:
        """Seconds until the level reaches ``target``."""
        if self.rate <= 0:
            return 1.0
        return max(0.0, (target - self.level) / self.rate)


class _TenantState:
    __slots__ = ("requests", "tokens", "seen_t")

    def __init__(self, quota: TenantQuota, now: float):
        self.requests = _Bucket(quota.requests_per_s, quota.burst_s, now)
        self.tokens = _Bucket(quota.tokens_per_s, quota.burst_s, now)
        self.seen_t = now


class TenantQuotas:
    """Single-loop discipline like the admission controller: all state
    mutation happens on the event loop; no locks."""

    def __init__(
        self,
        default: Optional[TenantQuota] = None,
        overrides: Optional[Dict[str, TenantQuota]] = None,
        clock: Callable[[], float] = time.monotonic,
        max_tracked: int = 1024,
        registry: Optional[MetricsRegistry] = None,
        admissions_registry: Optional[MetricsRegistry] = None,
    ):
        self.default = default or TenantQuota()
        self.overrides = dict(overrides or {})
        self.clock = clock
        self.max_tracked = max(1, max_tracked)
        self._tenants: Dict[str, _TenantState] = {}

        self.registry = registry or MetricsRegistry()
        # the quota outcome rides the SAME family the priority classes
        # shed on — when the edge also runs an AdmissionController,
        # bind ITS registry (get-or-create returns the one counter; two
        # registries each owning the family would double-render it).
        # Created lazily so a bind-before-traffic never leaves an empty
        # duplicate family on this object's own registry.
        self._admissions = None
        if admissions_registry is not None:
            self.bind_admissions(admissions_registry)
        self._sheds = self.registry.counter(
            "dynamo_registry_tenant_sheds_total",
            "Quota rejections, labelled tenant= and bucket="
            "requests|tokens",
        )
        self._fallbacks = self.registry.counter(
            "dynamo_registry_tenant_fallbacks_total",
            "Requests whose X-Tenant header was present but unusable "
            "(garbage or over-length) and degraded to the default tenant",
        )
        self._tokens_c = self.registry.counter(
            "dynamo_registry_tenant_tokens_total",
            "Streamed tokens charged against tenant= budgets",
        )

    def bind_admissions(self, registry: MetricsRegistry) -> None:
        """Count quota outcomes on another registry's
        ``dynamo_planner_admissions_total`` (the admission controller's)
        instead of this object's own — one family, one exposition."""
        self._admissions = registry.counter(
            "dynamo_planner_admissions_total",
            "Admission decisions by priority= class and outcome="
            "admitted|shed|queue_full|timeout|draining|quota",
        )

    def _admissions_counter(self):
        if self._admissions is None:
            self.bind_admissions(self.registry)
        return self._admissions

    # ---------- construction from flags ----------

    @classmethod
    def from_flags(cls, default_rps: float, default_tps: float,
                   overrides_path: Optional[str] = None,
                   burst_s: float = 2.0) -> "TenantQuotas":
        """CLI wiring: ``--tenant-rps/--tenant-tps`` defaults plus an
        optional JSON file ``{"tenant": {"requests_per_s": ..,
        "tokens_per_s": .., "burst_s": ..}, ...}`` of overrides.
        Read synchronously — this runs at process startup, not on the
        serving loop."""
        overrides = {}
        if overrides_path:
            with open(overrides_path) as f:
                raw = json.load(f)
            for name, spec in raw.items():
                if not _TENANT_RE.match(name):
                    raise ValueError(f"bad tenant name {name!r} in "
                                     f"{overrides_path}")
                overrides[name] = TenantQuota.from_wire(spec)
        return cls(
            default=TenantQuota(requests_per_s=default_rps,
                                tokens_per_s=default_tps,
                                burst_s=burst_s),
            overrides=overrides,
        )

    # ---------- the X-Priority-mirroring parse contract ----------

    def resolve(self, header_value: Optional[str]) -> str:
        """Header → tenant id. Absent → default; present-but-garbage →
        default WITH a counter (an operator should know clients send
        broken headers); any well-formed id is a first-class tenant
        with its own buckets — isolation must not require pre-
        registration."""
        if header_value:
            v = header_value.strip()
            if not _TENANT_RE.match(v):
                self._fallbacks.inc()
        return parse_tenant(header_value)

    # ---------- the buckets ----------

    def _quota_for(self, tenant: str) -> TenantQuota:
        return self.overrides.get(tenant, self.default)

    def _state(self, tenant: str) -> _TenantState:
        now = self.clock()
        state = self._tenants.get(tenant)
        if state is None:
            if len(self._tenants) >= self.max_tracked:
                self._evict_idle(now)
            state = self._tenants[tenant] = _TenantState(
                self._quota_for(tenant), now)
        state.seen_t = now
        return state

    def _evict_idle(self, now: float) -> None:
        """Drop the longest-idle tracked tenant — a bounded table, not
        an unbounded per-client-id map (an eviction forgives at most
        one burst window of debt)."""
        oldest = min(self._tenants, key=lambda t: self._tenants[t].seen_t)
        del self._tenants[oldest]

    def admit(self, tenant: str, request_id: str = "") -> None:
        """Charge one request; raises :class:`AdmissionRejected`
        (outcome ``quota``) when either bucket is exhausted."""
        now = self.clock()
        state = self._state(tenant)
        state.requests.refill(now)
        state.tokens.refill(now)
        if state.requests.rate > 0 and state.requests.level < 1.0:
            self._reject(tenant, "requests", state.requests.until(1.0))
        if state.tokens.rate > 0 and state.tokens.level <= 0.0:
            # overdrafted by a previous stream's actual usage: shed
            # until the refill pays the debt back past zero
            self._reject(tenant, "tokens", state.tokens.until(1.0))
        if state.requests.rate > 0:
            state.requests.level -= 1.0
        # deliberately NOT counted as outcome="admitted" here: on the
        # shared family the admission controller owns the admitted row
        # (counting both would double every accepted request); quotas
        # contribute only their own rejection outcome

    def _reject(self, tenant: str, bucket: str, wait_s: float) -> None:
        self._sheds.inc(tenant=tenant, bucket=bucket)
        self._admissions_counter().inc(tenant=tenant, outcome="quota")
        raise AdmissionRejected(
            f"tenant {tenant!r} exceeded its {bucket} quota — retry "
            f"after the bucket refills",
            retry_after_s=max(1.0, math.ceil(wait_s)),
            outcome="quota",
        )

    def charge_tokens(self, tenant: str, n: int = 1) -> None:
        """Post-admission accounting: actual streamed tokens drain the
        token bucket (possibly below zero — the overdraft delays the
        tenant's NEXT admission, never breaks the current stream)."""
        if n <= 0:
            return
        state = self._tenants.get(tenant)
        if state is None or state.tokens.rate <= 0:
            return
        state.tokens.refill(self.clock())
        state.tokens.level -= n
        self._tokens_c.inc(n, tenant=tenant)

    # ---------- introspection ----------

    def snapshot(self) -> Dict[str, dict]:
        now = self.clock()
        out = {}
        for name, state in sorted(self._tenants.items()):
            state.requests.refill(now)
            state.tokens.refill(now)
            out[name] = {
                "requests_level": round(state.requests.level, 2),
                "tokens_level": round(state.tokens.level, 2),
            }
        return out
