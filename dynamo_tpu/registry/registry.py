"""ModelRegistry: the frontend's live view over registered model cards.

Read half (:class:`ModelRegistry`): canonical name + alias resolution
with tenant visibility — ``resolve("llama-fast", tenant="acme")`` →
the pool name a request routes by, or ``None`` when the model is
unknown *or invisible to that tenant* (indistinguishable by design: a
404 must not leak another tenant's catalog). Fed by the frontend's
ModelWatcher as registry records come and go, so workers joining or
leaving a model's pool rebind routes without a frontend restart.

Write half (:class:`RegistryAdmin`): the ``POST/DELETE /admin/models``
and ``scripts/dynamoctl.py`` surface — writes the same discovery
records workers publish at startup (``llmctl`` analog), non-lease-
scoped so an operator's registration outlives the CLI process.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

import msgpack

from ..telemetry.registry import MetricsRegistry
from .cards import ModelCard

logger = logging.getLogger(__name__)


class ModelRegistry:
    """name/alias → :class:`ModelCard` live view, with change listeners
    (the pool manager subscribes to learn about new/removed pools)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self.cards: Dict[str, ModelCard] = {}
        self._aliases: Dict[str, str] = {}  # alias → canonical name
        self._listeners: List[Callable[[str, Optional[ModelCard]], None]] = []
        self.registry = registry or MetricsRegistry()
        self.registry.callback_gauge(
            "dynamo_registry_models_info",
            "1 per registered model card, labelled model= and family=",
            # dynrace: domain(executor)
            lambda: [
                ({"model": name, "family": card.family or "unknown"}, 1)
                for name, card in sorted(self.cards.items())
            ],
        )

    # ---------- mutation (ModelWatcher / tests) ----------

    def put(self, card: ModelCard) -> None:
        previous = self.cards.get(card.name)
        if previous is not None:
            for alias in previous.aliases:
                if self._aliases.get(alias) == previous.name:
                    del self._aliases[alias]
        self.cards[card.name] = card
        for alias in card.aliases:
            existing = self._aliases.get(alias)
            if existing is not None and existing != card.name:
                logger.warning(
                    "alias %r already points at model %r; %r keeps it",
                    alias, existing, existing)
                continue
            self._aliases[alias] = card.name
        self._notify(card.name, card)

    def remove(self, name: str) -> None:
        card = self.cards.pop(name, None)
        if card is None:
            return
        for alias in card.aliases:
            if self._aliases.get(alias) == name:
                del self._aliases[alias]
        self._notify(name, None)

    def add_listener(
        self, fn: Callable[[str, Optional[ModelCard]], None]
    ) -> None:
        """Subscribe to card changes: ``fn(name, card)`` on put,
        ``fn(name, None)`` on removal. Sync callbacks; one listener's
        failure must not starve the rest."""
        self._listeners.append(fn)

    def _notify(self, name: str, card: Optional[ModelCard]) -> None:
        for fn in list(self._listeners):
            try:
                fn(name, card)
            except Exception:
                logger.exception("registry listener failed for %s", name)

    # ---------- resolution ----------

    def lookup(self, model: str) -> Optional[str]:
        """name or alias → canonical name; None if unknown. Visibility
        is NOT consulted here — use :meth:`resolve` on request paths."""
        if model in self.cards:
            return model
        return self._aliases.get(model)

    def card(self, name: str) -> Optional[ModelCard]:
        return self.cards.get(name)

    def resolve(self, model: str, tenant: Optional[str] = None
                ) -> Optional[str]:
        """Request-path resolution: canonical pool name, or None when
        the model is unknown OR invisible to ``tenant`` (same answer —
        a tenant must not be able to probe another tenant's catalog)."""
        name = self.lookup(model)
        if name is None:
            return None
        return name if self.cards[name].visible_to(tenant) else None

    def visible(self, tenant: Optional[str] = None) -> List[str]:
        """Canonical names visible to ``tenant``, sorted."""
        return sorted(
            name for name, card in self.cards.items()
            if card.visible_to(tenant)
        )


class RegistryAdmin:
    """Dynamic model management over the discovery plane — the write
    half behind ``POST/DELETE /admin/models`` and ``dynamoctl``.

    Writes the same ``{ns}/models/{type}/{name}`` records workers
    publish at startup, but non-lease-scoped: an operator registration
    must outlive the admin request that created it."""

    def __init__(self, drt, namespace: str = "public"):
        self.drt = drt
        self.namespace = namespace

    def _key(self, model_type: str, name: str) -> str:
        # mirror http/service.py model_registry_key without importing it
        # (the http module imports this package)
        return f"{self.namespace}/models/{model_type}/{name}"

    async def add(self, card: ModelCard) -> None:
        from ..http.service import parse_endpoint_path

        parse_endpoint_path(card.endpoint)  # malformed addresses fail HERE
        entry = {
            "name": card.name,
            "endpoint": card.endpoint,
            "model_type": card.model_type,
            "card": card.to_wire(),
        }
        if card.context_length:
            entry["mdc"] = {"context_length": card.context_length}
        await self.drt.discovery.kv_put(
            self._key(card.model_type, card.name),
            msgpack.packb(entry, use_bin_type=True),
        )

    async def remove(self, name: str,
                     model_type: Optional[str] = None) -> None:
        """Delete the registration. Without ``model_type`` every type's
        key is deleted — a remove must never miss because the operator
        forgot which kind the model was added as."""
        types = [model_type] if model_type else ["chat", "completions",
                                                 "both"]
        for mt in types:
            await self.drt.discovery.kv_delete(self._key(mt, name))

    async def list(self) -> List[dict]:
        kvs = await self.drt.discovery.kv_get_prefix(
            f"{self.namespace}/models/")
        return [msgpack.unpackb(v, raw=False) for v in kvs.values()]
