"""PoolManager: per-model worker pools — scale-to-zero + cold starts.

One serving plane, many models: the frontend routes ``model=`` to a
per-model pool (http/service.py ModelWatcher → per-model clients over
the lease-scoped endpoint registry). This manager adds the elasticity:

- **scale-to-zero** — a :class:`~.policy.PoolPolicy` loop watches each
  model's demand (requests through this frontend, optionally the fleet
  hub's per-worker activity) and drains an idle model's workers to zero
  through the configured backend (PR 8's drain ladder on each worker,
  or a replica patch on the pool's deployment).
- **cold start** — the first request for a model whose pool is empty
  triggers a spawn *with that model's card* (respawn-with-different-
  card, the one new recovery capability) and waits, bounded by
  ``cold_start_deadline_s``, for a worker to join the pool; past the
  deadline the request is shed with 503 + Retry-After
  (:class:`ColdStartTimeout` at the HTTP edge).

Backends are two callables (``spawner(card)``, ``drainer(model)``) so
the same manager drives an InMemoryKube deployment in tests, the
api-store record a standalone operator reconciles, or a subprocess
respawn — :class:`KubePoolBackend` / :class:`StorePoolBackend` package
the replica-patch pair.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Awaitable, Callable, Dict, Optional

from ..llm.model_card import slugify
from ..telemetry.registry import MetricsRegistry
from .cards import ModelCard
from .policy import PoolDemand, PoolPolicy, PoolPolicyConfig
from .registry import ModelRegistry

logger = logging.getLogger(__name__)


class ColdStartTimeout(Exception):
    """No worker joined the cold model's pool within the deadline; the
    edge maps this to 503 + Retry-After."""

    def __init__(self, model: str, waited_s: float,
                 retry_after_s: float = 5.0):
        super().__init__(
            f"model {model!r} is cold and no worker came up within "
            f"{waited_s:.1f}s — retry later"
        )
        self.model = model
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class PoolConfig:
    # bounded wait for a cold pool's first worker (0 = fail immediately)
    cold_start_deadline_s: float = 30.0
    # Retry-After hint on a cold-start timeout
    retry_after_s: float = 5.0
    # policy loop cadence (scale-to-zero decisions)
    interval_s: float = 1.0
    # how often the cold-start wait re-checks the pool
    poll_s: float = 0.05
    # pacing for re-kicking a spawn attempt that FAILED while waiters
    # still hold the deadline (a crashing spawner must not hot-loop)
    retry_kick_s: float = 1.0


class _PoolState:
    __slots__ = ("last_request_t", "requests_total", "cold_task",
                 "cold_waiters", "last_kick_t")

    def __init__(self, now: float):
        self.last_request_t = now
        self.requests_total = 0
        self.cold_task: Optional[asyncio.Task] = None
        self.cold_waiters = 0        # requests holding a cold-start wait
        self.last_kick_t = -1e9      # spawn-attempt pacing


class PoolManager:
    def __init__(
        self,
        registry_view: ModelRegistry,
        pool_size: Callable[[str], int],
        spawner: Optional[Callable[[ModelCard], Awaitable]] = None,
        drainer: Optional[Callable[[str], Awaitable]] = None,
        config: Optional[PoolConfig] = None,
        policy: Optional[PoolPolicy] = None,
        clock: Callable[[], float] = time.monotonic,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.view = registry_view
        self.pool_size = pool_size
        self.spawner = spawner
        self.drainer = drainer
        self.config = config or PoolConfig()
        self.policy = policy or PoolPolicy(
            PoolPolicyConfig(idle_to_zero_s=0.0), clock=clock)
        self.clock = clock
        self._pools: Dict[str, _PoolState] = {}
        self._task: Optional[asyncio.Task] = None
        self.view.add_listener(self._on_card)
        # cards registered before this manager existed still get pools
        now = self.clock()
        for name in self.view.cards:
            self._pools.setdefault(name, _PoolState(now))

        self.registry = registry or MetricsRegistry()
        self.registry.callback_gauge(
            "dynamo_registry_pool_workers_replicas",
            "Live workers per model pool, labelled model=",
            # dynrace: domain(executor)
            lambda: [
                ({"model": name}, self.pool_size(name))
                for name in sorted(self._pools)
            ],
        )
        self._cold_starts = self.registry.counter(
            "dynamo_registry_cold_starts_total",
            "Cold-start attempts per model=, outcome="
            "started|completed|timeout|no_spawner",
        )
        self._zero_scales = self.registry.counter(
            "dynamo_registry_scale_to_zero_total",
            "Idle pools drained to zero replicas, labelled model=",
        )
        self._cold_wait = self.registry.histogram(
            "dynamo_registry_cold_start_wait_seconds",
            "Cold-start wait of requests that found their pool empty "
            "(admitted AND shed waits)",
        )

    # ---------- registry feed ----------

    def _on_card(self, name: str, card) -> None:
        if card is None:
            state = self._pools.pop(name, None)
            if state is not None and state.cold_task is not None:
                state.cold_task.cancel()
            return
        if name not in self._pools:
            # idle accounting starts at first sight, so a never-
            # requested pool still ages out
            self._pools[name] = _PoolState(self.clock())

    # ---------- demand signals ----------

    def note_request(self, model: str) -> None:
        state = self._pools.get(model)
        if state is None:
            if self.view.card(model) is None:
                # card-less engines (local single-model serving) are not
                # pool citizens: tracking them would let scale-to-zero
                # inject junk pool services into deployment records
                return
            state = self._pools[model] = _PoolState(self.clock())
        state.last_request_t = self.clock()
        state.requests_total += 1

    def demand(self) -> Dict[str, PoolDemand]:
        now = self.clock()
        return {
            name: PoolDemand(
                workers=self.pool_size(name),
                idle_s=now - state.last_request_t,
                # waiters count too: a FAILED spawn attempt with
                # requests still holding the deadline keeps the cold
                # pressure visible, so the policy loop re-kicks it
                cold_pending=(state.cold_waiters > 0
                              or (state.cold_task is not None
                                  and not state.cold_task.done())),
            )
            for name, state in self._pools.items()
        }

    def snapshot(self) -> list:
        """``GET /admin/pools`` rows."""
        now = self.clock()
        return [
            {
                "model": name,
                "workers": self.pool_size(name),
                "idle_s": round(now - state.last_request_t, 3),
                "requests_total": state.requests_total,
                "cold_starting": (state.cold_task is not None
                                  and not state.cold_task.done()),
            }
            for name, state in sorted(self._pools.items())
        ]

    # ---------- cold start ----------

    async def await_capacity(self, model: str) -> None:
        """Gate one request on the model's pool having a worker.

        A warm pool returns immediately. A cold pool triggers ONE spawn
        with the model's card (concurrent requests share it) and polls
        until a worker joins or the deadline passes — then raises
        :class:`ColdStartTimeout` (the 503 + Retry-After path).
        """
        if self.pool_size(model) > 0:
            return
        t0 = self.clock()
        state = self._pools.get(model)
        if state is None:
            state = self._pools[model] = _PoolState(t0)
        state.cold_waiters += 1
        try:
            self._kick_cold_start(model, state)
            deadline = t0 + self.config.cold_start_deadline_s
            while self.clock() < deadline:
                if self.pool_size(model) > 0:
                    self._cold_wait.observe(self.clock() - t0)
                    self._cold_starts.inc(model=model,
                                          outcome="completed")
                    return
                # a FAILED spawn attempt retries (paced) while the
                # deadline still holds — one crash must not burn every
                # waiter's whole budget
                self._kick_cold_start(model, state)
                await asyncio.sleep(self.config.poll_s)
            self._cold_wait.observe(self.clock() - t0)
            self._cold_starts.inc(model=model, outcome="timeout")
            raise ColdStartTimeout(
                model, self.clock() - t0,
                retry_after_s=self.config.retry_after_s)
        finally:
            state.cold_waiters -= 1

    def _kick_cold_start(self, model: str, state: _PoolState) -> None:
        """Start (or paced-retry) one spawn attempt. The spawner should
        be idempotent toward "one worker up" — replica patches are; the
        manager re-invokes it until the pool has a worker or every
        waiter's deadline expires."""
        if state.cold_task is not None and not state.cold_task.done():
            return  # a spawn is already in flight — requests share it
        now = self.clock()
        if now - state.last_kick_t < self.config.retry_kick_s:
            return  # pace attempts (and the no-spawner accounting)
        state.last_kick_t = now
        card = self.view.card(model)
        if card is None or self.spawner is None:
            self._cold_starts.inc(model=model, outcome="no_spawner")
            return
        self._cold_starts.inc(model=model, outcome="started")
        logger.info("cold start: spawning a worker for model %s", model)

        async def spawn() -> None:
            try:
                await self.spawner(card)
            except asyncio.CancelledError:
                raise
            except Exception:
                # the waiters' deadline is the real failure path; the
                # spawn error itself must be diagnosable, not silent
                logger.exception("cold-start spawn for %s failed", model)

        state.cold_task = asyncio.get_running_loop().create_task(
            spawn(), name=f"cold-start-{model}")

    # ---------- scale-to-zero loop ----------

    def start(self, spawn=None) -> "PoolManager":
        if self._task is None:
            spawn = spawn or asyncio.get_running_loop().create_task
            self._task = spawn(self._loop())
        return self

    async def _loop(self) -> None:
        while True:
            try:
                await self.step()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("pool policy step failed")
            await asyncio.sleep(self.config.interval_s)

    async def step(self) -> list:
        """One observe→decide→actuate pass; returns applied actions."""
        applied = []
        for action in self.policy.decide(self.demand()):
            if action.kind == "scale_to_zero":
                if self.drainer is None:
                    continue
                logger.info("scale-to-zero: draining idle pool %s",
                            action.model)
                try:
                    await self.drainer(action.model)
                except Exception:
                    logger.exception("draining pool %s failed",
                                     action.model)
                    continue
                self._zero_scales.inc(model=action.model)
                applied.append(action)
            elif action.kind == "cold_start":
                state = self._pools.get(action.model)
                if state is not None:
                    self._kick_cold_start(action.model, state)
                    applied.append(action)
        return applied

    async def stop(self) -> None:
        tasks = [t for t in [self._task] if t is not None]
        self._task = None
        for state in self._pools.values():
            if state.cold_task is not None and not state.cold_task.done():
                tasks.append(state.cold_task)
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)


# ---------------------------------------------------------------------------
# replica-patch backends (spawner/drainer pairs)
# ---------------------------------------------------------------------------


def pool_service_name(model: str) -> str:
    """CR/deployment service name of one model's pool."""
    return f"pool-{slugify(model)}"


def pool_service_spec(services: dict, model: str,
                      card: Optional[ModelCard] = None) -> dict:
    """Get-or-create one model pool's service spec in a CR/record
    ``services`` map: a decode-role worker deployment whose model flags
    come from the card (the cold-start material)."""
    service = pool_service_name(model)
    spec = services.setdefault(service, {"role": "decode"})
    if card is not None:
        if card.model_path:
            spec.setdefault("modelPath", card.model_path)
        spec.setdefault("modelName", card.name)
    return spec


class KubePoolBackend:
    """spawner/drainer over the deploy Reconciler: per-model pool
    services (decode-role worker deployments) in one CR, replicas
    patched 0↔N. ``InMemoryKube`` tests the loop end-to-end;
    Kubectl/KubeApi run it for real (the same split as
    planner/actuation.py KubeActuator)."""

    def __init__(self, reconciler, cr: dict, replicas: int = 1):
        self.reconciler = reconciler
        self.cr = cr
        self.replicas = replicas

    def _scale(self, model: str, replicas: int,
               card: Optional[ModelCard] = None) -> None:
        services = self.cr["spec"].setdefault("services", {})
        pool_service_spec(services, model, card)["replicas"] = int(replicas)

    async def _reconcile(self) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self.reconciler.reconcile, self.cr)

    async def spawn(self, card: ModelCard) -> None:
        self._scale(card.name, self.replicas, card)
        await self._reconcile()

    async def drain(self, model: str) -> None:
        self._scale(model, 0)
        await self._reconcile()


class StorePoolBackend:
    """Credless frontends: patch the pool's replica count into the
    api-store deployment record; the operator sourcing CRs from the
    store applies it on its next pass (planner StoreScaleActuator's
    pattern, per-model)."""

    def __init__(self, store_client, deployment: str, replicas: int = 1):
        self.store = store_client  # deploy.store_source.ApiStoreClient (sync)
        self.deployment = deployment
        self.replicas = replicas

    def _patch(self, model: str, replicas: int,
               card: Optional[ModelCard] = None) -> None:
        rec = self.store.get(self.deployment)
        if rec is None:
            logger.warning("deployment %r not in api-store — pool scale "
                           "skipped", self.deployment)
            return
        spec = rec["spec"]
        services = spec.setdefault("services", {})
        pool_service_spec(services, model, card)["replicas"] = int(replicas)
        self.store.update(self.deployment, spec)

    async def spawn(self, card: ModelCard) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            None, self._patch, card.name, self.replicas, card)

    async def drain(self, model: str) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self._patch, model, 0)
