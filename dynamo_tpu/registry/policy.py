"""PoolPolicy: deterministic per-model pool decisions.

The planner's multi-model half (ROADMAP item 3): given each model's
demand signals — pool size, seconds since the last request, whether a
cold start is pending — decide which idle pools to drain to zero and
which cold pools to start. Deliberately the same shape as
``planner/policy.py``'s SlaPolicy: pure ``decide()`` over a snapshot,
injectable clock, per-model cooldowns so a flapping demand signal can't
thrash a pool, and the caller (PoolManager or a standalone planner)
owns actuation.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Mapping, Optional


@dataclasses.dataclass
class PoolPolicyConfig:
    # a pool with no request for this long drains to zero (0 = never)
    idle_to_zero_s: float = 300.0
    # a nonzero floor DISABLES scale-to-zero for every pool: the only
    # drain this policy emits is to-zero (that's what the backends
    # implement), so a floor above zero means "never drain" rather than
    # silently draining past the floor
    min_workers: int = 0
    # per-model action pacing: a drained pool isn't re-drained, a
    # started pool isn't re-started, within the cooldown
    cooldown_s: float = 30.0


@dataclasses.dataclass
class PoolDemand:
    """One model's demand snapshot, as the caller observed it.

    ``idle_s`` counts from the last request OR from when the pool was
    first observed — a pool that never saw traffic still ages out."""

    workers: int                   # live pool size
    idle_s: float                  # seconds since the last request
    cold_pending: bool = False     # a request is waiting on a cold start


@dataclasses.dataclass
class PoolAction:
    model: str
    kind: str  # "scale_to_zero" | "cold_start"


class PoolPolicy:
    def __init__(self, config: Optional[PoolPolicyConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or PoolPolicyConfig()
        self.clock = clock
        self._last_action: Dict[str, float] = {}  # model → last action t

    def _cooled(self, model: str, now: float) -> bool:
        last = self._last_action.get(model)
        return last is None or (now - last) >= self.config.cooldown_s

    def decide(self, demand: Mapping[str, PoolDemand]) -> List[PoolAction]:
        cfg = self.config
        now = self.clock()
        actions: List[PoolAction] = []
        for model in sorted(demand):
            d = demand[model]
            if d.cold_pending and d.workers <= 0:
                # demand for a cold pool beats any idle accounting —
                # and beats the cooldown too: the request is WAITING
                actions.append(PoolAction(model, "cold_start"))
                self._last_action[model] = now
                continue
            if (cfg.idle_to_zero_s > 0
                    and cfg.min_workers == 0
                    and d.workers > 0
                    and d.idle_s >= cfg.idle_to_zero_s
                    and self._cooled(model, now)):
                actions.append(PoolAction(model, "scale_to_zero"))
                self._last_action[model] = now
        return actions
