"""Model registry + fleet routing plane: many models, many tenants.

The deployment-plane capability the reference exposes as ``llmctl http
add/remove`` + model deployment cards (PAPER.md §1 layers 3 and 7),
grown into a fleet feature:

- :mod:`cards` — :class:`ModelCard`: what a served model IS (name,
  family, context length, served aliases, tenant visibility) and where
  its pool lives (a dyn:// endpoint). Workers publish cards as
  lease-scoped discovery records at startup; operators add/remove them
  dynamically (``POST/DELETE /admin/models``, ``scripts/dynamoctl.py``).
- :mod:`registry` — :class:`ModelRegistry`: the frontend's live view
  over those records (alias resolution, tenant visibility) plus the
  :class:`RegistryAdmin` write half behind the admin API.
- :mod:`pools` — :class:`PoolManager`: per-model worker pools with
  scale-to-zero for idle models and bounded cold-start waits on first
  request for a cold one (503 + Retry-After past the deadline).
- :mod:`policy` — :class:`PoolPolicy`: the deterministic decide() the
  manager (or a standalone planner) runs over per-model demand.
- :mod:`tenants` — :class:`TenantQuotas`: ``X-Tenant`` admission
  classes with per-tenant token buckets (requests/s and tokens/s), so
  one tenant's spike sheds that tenant (429 + Retry-After) while the
  rest are untouched.
"""

from .cards import ModelCard, card_from_mdc
from .policy import PoolAction, PoolDemand, PoolPolicy, PoolPolicyConfig
from .pools import (
    ColdStartTimeout,
    KubePoolBackend,
    PoolConfig,
    PoolManager,
    StorePoolBackend,
)
from .registry import ModelRegistry, RegistryAdmin
from .tenants import (
    DEFAULT_TENANT,
    TENANT_HEADER,
    TenantQuota,
    TenantQuotas,
)

__all__ = [
    "ModelCard",
    "card_from_mdc",
    "ModelRegistry",
    "RegistryAdmin",
    "PoolManager",
    "PoolConfig",
    "PoolPolicy",
    "PoolPolicyConfig",
    "PoolAction",
    "PoolDemand",
    "ColdStartTimeout",
    "KubePoolBackend",
    "StorePoolBackend",
    "TenantQuotas",
    "TenantQuota",
    "TENANT_HEADER",
    "DEFAULT_TENANT",
]
