"""ModelCard: the registry's unit of truth for one served model.

Where :class:`~dynamo_tpu.llm.model_card.ModelDeploymentCard` describes
preprocessing agreement (tokenizer, template, checksum) for ONE engine,
the ModelCard describes the model as a *fleet citizen*: the name clients
route by, the served aliases, the family, which tenants may see it, and
the dyn:// endpoint its worker pool serves. Reference analog: the model
cards ``llmctl http add`` writes for the HTTP frontend's watcher
(reference: launch/llmctl/src/main.rs ModelEntry + lib/llm/src/
model_card/model.rs), extended with visibility + pool metadata.

Cards ride the SAME discovery records the frontend's ModelWatcher
already consumes (``{ns}/models/{type}/{name}``, http/service.py), as an
extra ``card`` field — a registry-less frontend keeps working, a
card-aware one becomes a live view (aliases, tenants, pools).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

MODEL_TYPES = ("chat", "completions", "both")


@dataclasses.dataclass
class ModelCard:
    name: str                      # canonical served name (the pool key)
    endpoint: str = ""             # dyn://ns.comp.ep of the pool
    model_type: str = "both"       # chat | completions | both
    family: Optional[str] = None   # llama / gemma2 / mixtral / ...
    context_length: Optional[int] = None
    aliases: List[str] = dataclasses.field(default_factory=list)
    # tenant visibility: None = public (every tenant), [] = admin-only
    # (nobody resolves it), else the allow list
    tenants: Optional[List[str]] = None
    owned_by: str = "dynamo"
    # cold-start material: enough for a respawn-with-this-card (the
    # recovery controller / pool backend rebuilds a worker from it)
    model_path: Optional[str] = None
    kv_block_size: Optional[int] = None
    # preprocessing-agreement checksum (ModelDeploymentCard.checksum):
    # lets a router verify two pool members agree before mixing streams
    checksum: Optional[str] = None

    def __post_init__(self) -> None:
        if self.model_type not in MODEL_TYPES:
            raise ValueError(
                f"model_type {self.model_type!r} not in {MODEL_TYPES}")

    def visible_to(self, tenant: Optional[str]) -> bool:
        """Public cards are visible to everyone (including requests with
        no tenant header); scoped cards only to listed tenants."""
        if self.tenants is None:
            return True
        return tenant is not None and tenant in self.tenants

    def served_names(self) -> List[str]:
        return [self.name] + [a for a in self.aliases if a != self.name]

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_wire(cls, d: dict) -> "ModelCard":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


def card_from_mdc(
    mdc,
    endpoint: str,
    name: Optional[str] = None,
    model_type: Optional[str] = None,
    aliases: Optional[List[str]] = None,
    tenants: Optional[List[str]] = None,
) -> ModelCard:
    """Build the fleet card from an engine's deployment card. The family
    is the HF architecture family (config.json ``model_type``) — the
    zoo key (models/__init__.py), not the chat/completions axis."""
    return ModelCard(
        name=name or mdc.display_name,
        endpoint=endpoint,
        model_type=model_type or getattr(mdc, "model_type", "both") or "both",
        family=(mdc.config or {}).get("model_type"),
        context_length=mdc.context_length,
        aliases=list(aliases or []),
        tenants=list(tenants) if tenants is not None else None,
        model_path=mdc.model_path,
        kv_block_size=mdc.kv_block_size,
        checksum=mdc.checksum,
    )
