"""dynamo-tpu: a TPU-native distributed LLM inference-serving framework.

Capabilities modeled on NVIDIA Dynamo (see SURVEY.md), rebuilt TPU-first:
an OpenAI-compatible frontend, a distributed runtime (lease-based discovery +
pub/sub messaging + TCP dial-back streaming), KV-cache-aware routing over a
global radix index, disaggregated prefill/decode with HBM-to-HBM KV transfer,
and a native JAX/XLA serving engine (paged attention, continuous batching,
pjit/shard_map parallelism) in place of GPU engines.
"""

__version__ = "0.1.0"
