"""Fused Pallas sampling epilogue: the per-step decode tail in ONE kernel.

Every decode step ends with the same ladder (engine/model_runner.py
``_sample_and_logprobs`` + the chained burst's finish checks): penalty
application against the slot's generated-count/prompt-presence rows,
temperature + top-k / top-p / min-p filtering, the categorical draw, the
sampled token's logprob, the penalty-count commit, and — in the chained
burst — the device-finish verdict (eos/stop-id/max-token/model-len) and
the stop-string suffix-ring rolling hash. As XLA ops that tail is a
string of small [B, V] kernels dispatched between the forward and the
next step's launch; at chained-burst cadence the launch overhead of the
tail is a visible slice of inter-token latency. This kernel runs the
whole tail as one ``pallas_call`` over a batch-row grid.

Bit-identity is by CONSTRUCTION, not by tolerance: the kernel body
executes the exact jnp op sequence of ``engine/sampling.sample`` (same
sort/argsort/cumsum/scatter calls, same masking order, same f32 math) on
each row, and the categorical draw uses the identity
``jax.random.categorical(key, logits) == argmax(gumbel(key, shape) +
logits)`` (that IS jax's implementation) with the per-row gumbel noise
precomputed OUTSIDE the kernel from the same ``_row_keys`` fold-in. In
interpret mode the body lowers to the same XLA ops the dense ladder
runs, so the token/logprob stream is bit-equal — the differential test
asserts exact equality, and the TPU path is gated by the ``epilogue``
compile probe (ops/probe.py) like every other Mosaic specialization.

The penalty-count commit writes through an aliased counts buffer whose
block index is the row's sample slot (scalar-prefetched). That in-place
form requires each grid step to own its output row, so it only engages
when the caller guarantees unique slots (``alias_counts=True`` — the
decode/burst paths, whose slots are ``arange``); the batched-prefill
step, whose pad rows share slot 0 with a potentially live row, keeps the
commit as a scatter-add outside the kernel.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_decode import _compiler_params

LANE = 128


def _epilogue_kernel(
    slots_ref,     # scalar prefetch: sample slot per batch row [B] (SMEM)
    logits_ref,    # [1, V] the row's raw head logits
    bias_ref,      # [1, V] f32 — the slot's persistent logit_bias row
    *rest,
    v: int,
    max_model_len: int,
    has_extra: bool,
    with_finish: bool,
    alias_counts: bool,
    hash_p: int,
    max_suffix_len: int,
):
    if has_extra:
        extra_ref, *rest = rest
    gum_ref, fpar_ref, ipar_ref, cin_ref, seen_ref, *rest = rest
    if with_finish:
        sid_ref, ring_ref, shash_ref, slen_ref, *rest = rest
    if alias_counts:
        cout_ref, *rest = rest
    tok_ref, lp_ref, *rest = rest
    if with_finish:
        hard_ref, cand_ref, rout_ref = rest

    # ---- exact op-for-op mirror of engine/sampling.sample on one row ----
    raw = logits_ref[0].astype(jnp.float32)
    rb = bias_ref[0]
    if has_extra:
        rb = rb + extra_ref[0]
    logits = raw + rb

    cnt = cin_ref[0]
    generated = cnt > 0
    ever = generated | seen_ref[0]
    rp = fpar_ref[0, 5]
    logits = jnp.where(
        ever, jnp.where(logits > 0, logits / rp, logits * rp), logits
    )
    logits = logits - fpar_ref[0, 4] * cnt.astype(jnp.float32)
    logits = logits - fpar_ref[0, 3] * generated.astype(jnp.float32)

    greedy = jnp.argmax(logits)

    temp = jnp.maximum(fpar_ref[0, 0], 1e-6)
    scaled = logits / temp

    tk = ipar_ref[0, 0]
    sorted_desc = jnp.sort(scaled)[::-1]
    kth = sorted_desc[jnp.clip(tk - 1, 0, v - 1)]
    scaled = jnp.where((tk > 0) & (scaled < kth), -jnp.inf, scaled)

    probs_all = jax.nn.softmax(scaled)
    scaled = jnp.where(
        probs_all < fpar_ref[0, 2] * probs_all.max(), -jnp.inf, scaled
    )

    sort_idx = jnp.argsort(scaled)[::-1]
    sorted_scaled = scaled[sort_idx]
    probs = jax.nn.softmax(sorted_scaled)
    cum = jnp.cumsum(probs)
    keep_sorted = cum - probs < fpar_ref[0, 1]
    keep = jnp.zeros((v,), jnp.bool_).at[sort_idx].set(keep_sorted)
    scaled = jnp.where(keep, scaled, -jnp.inf)

    # categorical(key, l) IS argmax(gumbel(key) + l); the gumbel row was
    # drawn outside from the identical _row_keys fold-in
    sampled = jnp.argmax(gum_ref[0] + scaled)
    nt = jnp.where(fpar_ref[0, 0] <= 0.0, greedy, sampled).astype(jnp.int32)

    # chosen-token logprob from the UNPENALIZED biased logits — the same
    # log_softmax the dense tail shares with its top-K branch
    lp = jax.nn.log_softmax(raw + rb)[nt]

    live = ipar_ref[0, 1] > 0
    if alias_counts:
        onehot = (
            jax.lax.broadcasted_iota(jnp.int32, (1, v), 1)[0] == nt
        ).astype(jnp.int32)
        cout_ref[0] = cnt + jnp.where(live, onehot, 0)

    tok_ref[0] = jnp.broadcast_to(nt, (LANE,))
    lp_ref[0] = jnp.broadcast_to(lp, (LANE,))

    if not with_finish:
        return

    # ---- device_finish_mask + ring_push + stop_candidate_mask ----
    gen_n = ipar_ref[0, 2] + ipar_ref[0, 1]
    pos = ipar_ref[0, 3]
    min_new = ipar_ref[0, 4]
    max_new = ipar_ref[0, 5]
    hit = (nt == sid_ref[0]).any()
    hard = ((gen_n >= min_new) & hit) | (gen_n >= max_new) | (
        pos + 2 >= max_model_len
    )
    hard_ref[0] = jnp.broadcast_to(hard.astype(jnp.int32), (LANE,))

    ring_row = ring_ref[0]
    shifted = jnp.concatenate([ring_row[1:], nt[None].astype(ring_row.dtype)])
    ring_n = jnp.where(live, shifted, ring_row)
    rout_ref[0] = ring_n

    # rolling polynomial suffix hashes, uint32 wraparound — the exact
    # arithmetic of sampling.suffix_hashes unrolled on one row
    w = ring_n.shape[0]
    toks_u = ring_n.astype(jnp.uint32) + jnp.uint32(1)
    hs = [jnp.uint32(0)]
    p_pow = jnp.uint32(1)
    for ell in range(1, max_suffix_len + 1):
        hs.append(hs[-1] + toks_u[w - ell] * p_pow)
        p_pow = p_pow * jnp.uint32(hash_p)
    hlen = slen_ref[0]                              # [NS] i32
    sel = jnp.zeros(hlen.shape, jnp.uint32)
    for ell in range(0, max_suffix_len + 1):
        sel = jnp.where(hlen == ell, hs[ell], sel)
    cand = (
        (hlen > 0)
        & (gen_n >= hlen)
        & (gen_n >= min_new)
        & (sel == shash_ref[0])
    ).any()
    cand_ref[0] = jnp.broadcast_to(cand.astype(jnp.int32), (LANE,))


def fused_sampling_epilogue(
    last_logits: jax.Array,   # [B, V] head output for the step
    gumbel: jax.Array,        # [B, V] f32 per-row gumbel noise (see above)
    samp_scalars: Tuple,      # (temperature, top_k, top_p, min_p,
                              #  presence, frequency, repetition) — [B] each
    counts: jax.Array,        # [num_slots, V] i32 generated-token counts
    seen: jax.Array,          # [num_slots, V] bool prompt presence
    bias: jax.Array,          # [num_slots, V] f32 logit_bias rows
    sample_slots: jax.Array,  # [B] i32 — each row's slot
    commit: jax.Array,        # [B] bool — live rows (gates the count
                              # commit, the ring push, and gen_n)
    extra_bias: Optional[jax.Array] = None,  # [B, V] in-program bias (guided)
    finish: Optional[Tuple] = None,
    # finish = (gen, pos, min_new, max_new, stop_ids, ring,
    #           stop_hash, stop_hlen) — the chained burst's carry rows
    max_model_len: int = 0,
    alias_counts: bool = True,
    interpret: bool = False,
):
    """One-dispatch decode tail. Returns ``(next_tokens [B] i32,
    lps [B] f32, counts)`` — plus ``(hard [B] bool, cand [B] bool,
    ring_new [B, W])`` when ``finish`` is given. Token/logprob values are
    bit-identical to the unfused ``sample`` + ``log_softmax`` ladder."""
    from ..engine.sampling import _HASH_P, STOP_SEQ_MAX_LEN

    b, v = last_logits.shape
    ns = counts.shape[0]
    has_extra = extra_bias is not None
    with_finish = finish is not None
    temperature, top_k, top_p, min_p, presence, frequency, repetition = (
        samp_scalars
    )
    fpar = jnp.stack(
        [temperature, top_p, min_p, presence, frequency, repetition], axis=1
    ).astype(jnp.float32)
    icols = [top_k.astype(jnp.int32), commit.astype(jnp.int32)]
    if with_finish:
        gen, pos, min_new, max_new, stop_ids, ring, stop_hash, stop_hlen = (
            finish
        )
        icols += [gen.astype(jnp.int32), pos.astype(jnp.int32),
                  min_new.astype(jnp.int32), max_new.astype(jnp.int32)]
    ipar = jnp.stack(icols, axis=1)

    def row(i, s):
        return (i, 0)

    def slot_row(i, s):
        return (s[i], 0)

    in_specs = [
        pl.BlockSpec((1, v), row),                       # logits
        pl.BlockSpec((1, v), slot_row),                  # bias
    ]
    operands = [last_logits, bias]
    if has_extra:
        in_specs.append(pl.BlockSpec((1, v), row))
        operands.append(extra_bias)
    in_specs += [
        pl.BlockSpec((1, v), row),                       # gumbel
        pl.BlockSpec((1, fpar.shape[1]), row),           # fpar
        pl.BlockSpec((1, ipar.shape[1]), row),           # ipar
        pl.BlockSpec((1, v), slot_row),                  # counts
        pl.BlockSpec((1, v), slot_row),                  # seen
    ]
    operands += [gumbel.astype(jnp.float32), fpar, ipar, counts, seen]
    if with_finish:
        in_specs += [
            pl.BlockSpec((1, stop_ids.shape[1]), row),
            pl.BlockSpec((1, ring.shape[1]), row),
            pl.BlockSpec((1, stop_hash.shape[1]), row),
            pl.BlockSpec((1, stop_hlen.shape[1]), row),
        ]
        operands += [stop_ids, ring, stop_hash.astype(jnp.uint32),
                     stop_hlen.astype(jnp.int32)]

    out_shape, out_specs, aliases = [], [], {}
    if alias_counts:
        # flattened-operand index of counts: slots + logits + bias
        # [+ extra] + gumbel + fpar + ipar
        aliases[6 + int(has_extra)] = 0
        out_shape.append(jax.ShapeDtypeStruct((ns, v), counts.dtype))
        out_specs.append(pl.BlockSpec((1, v), slot_row))
    out_shape += [
        jax.ShapeDtypeStruct((b, LANE), jnp.int32),
        jax.ShapeDtypeStruct((b, LANE), jnp.float32),
    ]
    out_specs += [pl.BlockSpec((1, LANE), row), pl.BlockSpec((1, LANE), row)]
    if with_finish:
        out_shape += [
            jax.ShapeDtypeStruct((b, LANE), jnp.int32),
            jax.ShapeDtypeStruct((b, LANE), jnp.int32),
            jax.ShapeDtypeStruct((b, ring.shape[1]), ring.dtype),
        ]
        out_specs += [
            pl.BlockSpec((1, LANE), row),
            pl.BlockSpec((1, LANE), row),
            pl.BlockSpec((1, ring.shape[1]), row),
        ]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    outs = pl.pallas_call(
        functools.partial(
            _epilogue_kernel,
            v=v,
            max_model_len=max_model_len,
            has_extra=has_extra,
            with_finish=with_finish,
            alias_counts=alias_counts,
            hash_p=int(_HASH_P),
            max_suffix_len=STOP_SEQ_MAX_LEN,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        # sequential grid: the aliased counts row of a pad row may
        # duplicate another row's slot; arbitrary (not parallel) order
        # keeps the read-modify-write of each block well-defined
        compiler_params=_compiler_params(
            dimension_semantics=("arbitrary",),
        ),
        input_output_aliases=aliases,
        interpret=interpret,
    )(sample_slots.astype(jnp.int32), *operands)

    outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
    if alias_counts:
        counts = outs.pop(0)
    nt = outs.pop(0)[:, 0]
    lps = outs.pop(0)[:, 0]
    if not alias_counts:
        counts = counts.at[sample_slots, nt].add(commit.astype(jnp.int32))
    if not with_finish:
        return nt, lps, counts
    hard = outs.pop(0)[:, 0] > 0
    cand = outs.pop(0)[:, 0] > 0
    ring_new = outs.pop(0)
    return nt, lps, counts, hard, cand, ring_new
