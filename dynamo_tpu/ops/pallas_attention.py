"""Pallas TPU paged flash attention over the block-paged KV cache.

Replaces ops/attention.py's XLA gather path on TPU: instead of
materializing the gathered [B, W*bs, KVH, D] keys in HBM, the kernel
streams cache pages HBM→VMEM through the Pallas pipeline (the page
index_map reads the scalar-prefetched block table, so the gather IS the
pipeline's double-buffered DMA) and runs an online-softmax (flash)
accumulation in VMEM scratch. One grid step = one cache page for one
(batch row, query chunk): all KV heads of that page are processed so the
page DMA is one contiguous [bs, KVH, D] burst.

Reference analog: the vLLM/SGLang GPU paged-attention kernels the
reference delegated to (SURVEY.md §2.4, §7 hard-part #1).

API contract (matches the engine's scheduler): query positions of a step
are affine — token s of the q block sits at absolute position
``base_pos + s``. Pad rows past the true suffix produce garbage rows the
caller discards (their causal mask is wider but bounded by context_lens).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MASK_VALUE = -1e30

from .pallas_decode import (  # noqa: E402  (shared kernel-compat helpers)
    _compiler_params,
    _out_struct,
)


def _kernel(
    bt_ref,     # scalar prefetch: block tables [B, W]
    ctx_ref,    # scalar prefetch: context lens [B]
    base_ref,   # scalar prefetch: base query position [B]
    li_ref,     # scalar prefetch: layer index [1] (consumed by index_maps)
    win_ref,    # scalar prefetch: sliding window [1] (>= ctx disables)
    q_ref,      # [1, Sc, KVH, G, D] (VMEM block)
    k_ref,      # [1, 1, bs, KVH, D] — one cache page of one layer
    v_ref,
    *rest,      # ([sinks_ref [1, KVH, G] when has_sinks], o_ref, m/l/acc scratch)
    scale: float,
    block_size: int,
    softcap: float,
    has_sinks: bool = False,
):
    if has_sinks:
        sinks_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
    b = pl.program_id(0)
    c = pl.program_id(1)
    w = pl.program_id(2)
    num_w = pl.num_programs(2)

    _, sc, kvh, g, d = q_ref.shape
    rows = sc * g

    @pl.when(w == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, MASK_VALUE)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    ctx = ctx_ref[b]
    base = base_ref[b]
    window = win_ref[0]
    page_start = w * block_size
    chunk_base = base + c * sc  # absolute position of this chunk's row 0

    # page live iff it holds context AND is causally visible to the chunk
    # AND (with a window) its last key is within window of some chunk query
    live = jnp.logical_and(page_start < ctx, page_start <= chunk_base + sc - 1)
    live = jnp.logical_and(
        live, page_start + block_size + window > chunk_base + 1
    )

    @pl.when(live)
    def _compute():
        # lanes = key slot in page; sublanes = (s_local, group) query row
        key_pos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_size), 1
        )
        qpos = chunk_base + jax.lax.broadcasted_iota(
            jnp.int32, (rows, block_size), 0
        ) // g
        mask = jnp.logical_and(key_pos <= qpos, key_pos < ctx)
        mask = jnp.logical_and(mask, key_pos > qpos - window)

        for h in range(kvh):
            lo = h * rows
            q = q_ref[0, :, h, :, :].reshape(rows, d)          # [rows, D]
            # upcast from the cache storage dtype (fp8 serving)
            k = k_ref[0, 0, :, h, :].astype(q.dtype)            # [bs, D]
            v = v_ref[0, 0, :, h, :].astype(q.dtype)

            s_log = jax.lax.dot_general(
                q, k,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                           # [rows, bs]
            if softcap:
                s_log = softcap * jnp.tanh(s_log / softcap)
            s_log = jnp.where(mask, s_log, MASK_VALUE)

            m_prev = m_scr[lo : lo + rows, 0:1]                 # [rows, 1]
            l_prev = l_scr[lo : lo + rows, 0:1]
            m_cur = jnp.max(s_log, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s_log - m_new)                          # [rows, bs]
            l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)

            pv = jax.lax.dot_general(
                p.astype(v.dtype), v,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                                   # [rows, D]
            acc_scr[lo : lo + rows, :] = acc_scr[lo : lo + rows, :] * alpha + pv
            m_scr[lo : lo + rows, :] = jnp.broadcast_to(m_new, (rows, 128))
            l_scr[lo : lo + rows, :] = jnp.broadcast_to(l_new, (rows, 128))

    @pl.when(w == num_w - 1)
    def _finalize():
        for h in range(kvh):
            lo = h * rows
            l = l_scr[lo : lo + rows, 0:1]
            if has_sinks:
                # virtual sink key: denominator-only (any shared exp
                # shift cancels, so the keys-only running max serves)
                sk = jnp.broadcast_to(
                    sinks_ref[0, h][None, :], (sc, g)
                ).reshape(rows, 1)
                l = l + jnp.exp(sk - m_scr[lo : lo + rows, 0:1])
            l = jnp.where(l == 0.0, 1.0, l)
            out = (acc_scr[lo : lo + rows, :] / l).astype(o_ref.dtype)
            o_ref[0, :, h, :, :] = out.reshape(sc, g, d)


@functools.partial(
    jax.jit, static_argnames=("scale", "q_chunk", "interpret", "softcap")
)
def paged_flash_attention(
    q: jax.Array,            # [B, S, H, D] (post-RoPE)
    k_cache: jax.Array,      # [N_blocks, bs, KVH, D] or stacked [L, N, bs, KVH, D]
    v_cache: jax.Array,
    block_tables: jax.Array, # [B, W] int32
    base_pos: jax.Array,     # [B] int32 — absolute position of q[:, 0]
    context_lens: jax.Array, # [B] int32
    layer_idx=None,          # scalar int32 into L (default 0)
    scale: Optional[float] = None,
    q_chunk: int = 128,
    interpret: bool = False,
    softcap: float = 0.0,    # Gemma-2: logits ← cap·tanh(logits/cap)
    window=None,             # sliding window (int or traced scalar); None = off
    sinks=None,              # [H] per-head sink logits (GPT-OSS); None = off
) -> jax.Array:
    b, s, h, d = q.shape
    if k_cache.ndim == 4:
        k_cache, v_cache = k_cache[None], v_cache[None]
    _, n_blocks, block_size, kvh, _ = k_cache.shape
    li = (
        jnp.zeros((1,), jnp.int32)
        if layer_idx is None
        else jnp.asarray(layer_idx, jnp.int32).reshape(1)
    )
    win = (
        jnp.full((1,), jnp.int32(2**30))
        if window is None
        else jnp.asarray(window, jnp.int32).reshape(1)
    )
    w = block_tables.shape[1]
    g = h // kvh
    if scale is None:
        scale = d ** -0.5

    # largest divisor of S that fits the chunk budget (buckets are usually
    # powers of two, giving sc == q_chunk; odd max_model_len still works)
    sc = next(c for c in range(min(s, q_chunk), 0, -1) if s % c == 0)
    num_chunks = s // sc

    qg = q.reshape(b, num_chunks, sc, kvh, g, d)  # chunk dim explicit
    # re-flatten chunks into the grid: block index_map picks (b, c)
    qg = qg.reshape(b * num_chunks, sc, kvh, g, d)

    def last_needed_page(b_idx, c, ctx_ref, base_ref):
        # furthest page this (b, chunk) can touch — clamping the page grid
        # index to it makes trailing steps re-request the same page, which
        # the pipeline skips (no DMA) and the kernel skips (not live).
        by_ctx = jnp.maximum(ctx_ref[b_idx] - 1, 0) // block_size
        by_causal = jnp.maximum(base_ref[b_idx] + (c + 1) * sc - 1, 0) // block_size
        return jnp.minimum(by_ctx, by_causal)

    def first_needed_page(b_idx, c, base_ref, win_ref):
        # nearest page a windowed chunk can see: the chunk's first query
        # (at base + c*sc) sees nothing before base + c*sc - window + 1.
        # Window off (2**30) clamps to page 0. Leading grid steps re-fetch
        # this page; the pipeline skips the repeat DMAs and the kernel's
        # live predicate skips their compute.
        lo = base_ref[b_idx] + c * sc - win_ref[0] + 1
        return jnp.maximum(lo, 0) // block_size

    def q_map(i, c, wi, bt, ctx, base, li, win):
        return (i * num_chunks + c, 0, 0, 0, 0)

    def kv_map(i, c, wi, bt, ctx, base, li, win):
        wi = jnp.minimum(wi, last_needed_page(i, c, ctx, base))
        wi = jnp.maximum(wi, first_needed_page(i, c, base, win))
        return (li[0], bt[i, wi], 0, 0, 0)

    has_sinks = sinks is not None
    in_specs = [
        pl.BlockSpec((1, sc, kvh, g, d), q_map),
        pl.BlockSpec((1, 1, block_size, kvh, d), kv_map),
        pl.BlockSpec((1, 1, block_size, kvh, d), kv_map),
    ]
    if has_sinks:
        in_specs.append(
            pl.BlockSpec((1, kvh, g), lambda *_: (0, 0, 0))
        )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(b, num_chunks, w),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, sc, kvh, g, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((kvh * sc * g, 128), jnp.float32),
            pltpu.VMEM((kvh * sc * g, 128), jnp.float32),
            pltpu.VMEM((kvh * sc * g, d), jnp.float32),
        ],
    )

    operands = [
        block_tables.astype(jnp.int32),
        context_lens.astype(jnp.int32),
        base_pos.astype(jnp.int32),
        li,
        win,
        qg,
        k_cache,
        v_cache,
    ]
    if has_sinks:
        operands.append(jnp.asarray(sinks, jnp.float32).reshape(1, kvh, g))

    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, block_size=block_size, softcap=softcap,
            has_sinks=has_sinks,
        ),
        grid_spec=grid_spec,
        out_shape=_out_struct(
            (b * num_chunks, sc, kvh, g, d), q.dtype, q, k_cache,
        ),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*operands)
    return out.reshape(b, s, h, d)
