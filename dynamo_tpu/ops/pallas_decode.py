"""Pallas TPU decode-specialized paged attention (S == 1).

Why a second kernel: decode dominates serving time and has a degenerate
shape — one query token per sequence attending to the whole paged
context. The general kernel (ops/pallas_attention.py) drives its page
walk with a grid dimension sized to the block-table *capacity* W, so a
sequence with 32 live pages still pays W=128 grid steps of machinery per
layer (profiled at ~0.9 ms/layer on v5e for the 1B flagship — 40x the
bandwidth bound). Here the page walk is a data-dependent ``fori_loop``
bounded by ``ceil(context_len / page)`` inside a grid of just B steps:
work is proportional to *live* context, not capacity.

Mechanics: the paged KV cache stays in HBM (``memory_space=ANY``); the
kernel pulls pages VMEM-ward itself with double-buffered async copies
(``pltpu.make_async_copy``) — page indices come from the scalar-prefetched
block table, so the indirection rides the DMA engine, and compute on
chunk c overlaps the fetch of chunk c+1. The cache may be the engine's
full stacked-by-layer array ([L, N, page, KVH, D]); the layer to read is
a runtime index (``layer_idx``) so the per-layer ``lax.scan`` over the
transformer trunk needs no per-layer cache slicing (which XLA
materializes as a copy of the whole layer).

Reference analog: the decode-path paged-attention kernels of the GPU
engines the reference delegates to (SURVEY.md §2.4); same role as
vLLM's paged_attention_v2 CUDA kernel, reimagined for the TPU memory
system (explicit HBM→VMEM pipeline instead of SM shared memory).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MASK_VALUE = -1e30


def _out_vma(*arrays):
    """Varying-manual-axes annotation for pallas out_shape: the output
    varies over every manual mesh axis any input varies over. Needed so
    the kernels compose with ``check_vma=True`` shard_maps (the
    partial-manual pipeline in parallel/pipeline.py); None outside
    shard_map tracing, preserving plain-jit behavior. Older jax builds
    without ``jax.typeof`` get the plain-jit behavior unconditionally
    (no vma annotation — shard_map callers there run check_vma=False)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return None
    vma = frozenset().union(*(typeof(a).vma for a in arrays))
    return vma or None


# CompilerParams was TPUCompilerParams on older jax builds (the same
# vintage that lacks jax.typeof); resolve once so every kernel compiles
# on either
_compiler_params = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def _out_struct(shape, dtype, *arrays) -> jax.ShapeDtypeStruct:
    """out_shape with the vma annotation when the jax build supports it
    (newer jax; required for check_vma=True shard_maps) and a plain
    struct otherwise — older builds reject the ``vma`` kwarg outright,
    and there the annotation has nothing to annotate anyway."""
    vma = _out_vma(*arrays)
    if vma is None:
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _decode_kernel(
    bt_ref,    # scalar prefetch: block tables [B, W] (SMEM)
    ctx_ref,   # scalar prefetch: context lens [B]
    li_ref,    # scalar prefetch: layer index [1]
    win_ref,   # scalar prefetch: sliding window [1] (>= ctx disables)
    q_ref,     # [1, KVH, G, D] VMEM block
    k_hbm,     # [L, N, page, KVH, D] in HBM (ANY)
    v_hbm,
    *rest,     # ([sinks_ref [1, rows] when has_sinks], o_ref, scratch...)
    scale: float,
    block_size: int,
    pages_per_chunk: int,
    softcap: float,
    has_sinks: bool = False,
):
    """One grid step = one batch row; a fori_loop walks only LIVE chunks.

    Compute is ONE pair of MXU dots per chunk for ALL kv heads: the chunk
    KV flattens to [chunk_t * KVH, D] and every q row scores against every
    (token, head) column; a head-match mask (+ the validity mask) drives
    cross-head scores to MASK_VALUE, so their softmax weight is exactly 0
    and the single probs @ V dot sums only same-head contributions. This
    trades KVH× redundant MXU flops (trivial at decode shapes) for not
    issuing KVH tiny [G, chunk] dots per chunk — decode attention is DMA
    bound; op-issue overhead was the previous kernel's limiter.

    With a sliding window the walk starts at the first chunk holding a
    visible key (the decode query sits at ctx-1, so only positions in
    [ctx - window, ctx) matter): windowed decode costs O(window) DMA,
    not O(context) — the gathered XLA path always pays full width.

    ``has_sinks`` (GPT-OSS): a learned per-row logit joins the softmax
    as a virtual key with no value — one exp(sink - m) term added to
    the denominator at finalize.
    """
    if has_sinks:
        sinks_ref, o_ref, k_buf, v_buf, sem = rest
    else:
        o_ref, k_buf, v_buf, sem = rest
    b = pl.program_id(0)
    ctx = ctx_ref[b]
    li = li_ref[0]
    npages = pl.cdiv(ctx, block_size)          # live pages (ctx >= 1 in decode)
    nchunks = pl.cdiv(npages, pages_per_chunk)
    # first key position the decode query (at ctx-1) can see
    win_start = jnp.maximum(ctx - win_ref[0], 0)

    _, kvh, g, d = q_ref.shape
    rows = kvh * g
    chunk_t = pages_per_chunk * block_size
    cols = chunk_t * kvh

    def page_copy(chunk, slot, i, hbm, buf):
        # pages past the live range duplicate the last live page — their
        # key positions land >= ctx and the mask kills them.
        p = jnp.minimum(chunk * pages_per_chunk + i, npages - 1)
        return pltpu.make_async_copy(
            hbm.at[li, bt_ref[b, p]], buf.at[slot, i], sem.at[slot]
        )

    def start(chunk, slot):
        for i in range(pages_per_chunk):
            page_copy(chunk, slot, i, k_hbm, k_buf).start()
            page_copy(chunk, slot, i, v_hbm, v_buf).start()

    def wait(chunk, slot):
        for i in range(pages_per_chunk):
            page_copy(chunk, slot, i, k_hbm, k_buf).wait()
            page_copy(chunk, slot, i, v_hbm, v_buf).wait()

    first_chunk = win_start // chunk_t         # 0 when the window is off
    start(first_chunk, jax.lax.rem(first_chunk, 2))
    q = q_ref[0].reshape(rows, d)  # [KVH*G, D], rows ordered (head, group)

    # column j of the flattened chunk is (token j // KVH, head j % KVH);
    # row r serves head r // G — both masks are plain iota arithmetic
    col_head = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1) % kvh
    row_head = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0) // g
    head_match = col_head == row_head                    # loop-invariant
    col_tok = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1) // kvh

    def body(c, carry):
        m, l, acc = carry                                 # [rows,128]x2, [rows,D]
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nchunks)
        def _prefetch():
            start(c + 1, jax.lax.rem(c + 1, 2))

        wait(c, slot)
        # upcast from the cache storage dtype (fp8 serving stores e4m3;
        # the dots and the p·V product must run at the compute dtype)
        k = k_buf[slot].reshape(cols, d).astype(q.dtype)  # [(tok, head), D]
        v = v_buf[slot].reshape(cols, d).astype(q.dtype)

        # decode causality: the query is the newest token, so every key
        # with position < ctx is visible — a pure validity mask (plus the
        # window's lower bound; win_start == 0 when the window is off).
        key_pos = c * chunk_t + col_tok
        mask = head_match & (key_pos < ctx) & (key_pos >= win_start)

        s_log = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                         # [rows, cols]
        if softcap:
            s_log = softcap * jnp.tanh(s_log / softcap)
        s_log = jnp.where(mask, s_log, MASK_VALUE)

        m_cur = jnp.max(s_log, -1, keepdims=True)         # [rows, 1]
        m_new = jnp.maximum(m, m_cur)                     # [rows, 128]
        alpha = jnp.exp(m - m_new)
        p_unn = jnp.exp(s_log - m_new[:, 0:1])            # [rows, cols]
        l_new = alpha * l + jnp.sum(p_unn, -1, keepdims=True)
        pv = jax.lax.dot_general(
            p_unn.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                 # [rows, D]
        return m_new, l_new, acc * alpha[:, 0:1] + pv

    # m/l ride as [rows, 128] lane-broadcast carries (the layout Mosaic
    # handles without sub-lane-width relayouts; same trick as the scratch
    # accumulators in pallas_attention.py)
    m0 = jnp.full((rows, 128), MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((rows, 128), jnp.float32)
    acc0 = jnp.zeros((rows, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(first_chunk, nchunks, body, (m0, l0, acc0))
    l1 = l[:, 0:1]
    if has_sinks:
        # the sink is a virtual key with no value: denominator only.
        # Any shared shift works for the exp terms (it cancels), so the
        # keys-only running max m serves without a combined-max pass.
        l1 = l1 + jnp.exp(
            sinks_ref[0][:, None].astype(jnp.float32) - m[:, 0:1]
        )
    l1 = jnp.where(l1 == 0.0, 1.0, l1)
    o_ref[0] = (acc / l1).astype(o_ref.dtype).reshape(kvh, g, d)


def _mla_decode_kernel(
    bt_ref,    # scalar prefetch: block tables [B, W]
    ctx_ref,   # scalar prefetch: context lens [B]
    li_ref,    # scalar prefetch: layer index [1]
    ql_ref,    # [1, H, R]   latent-absorbed queries
    qr_ref,    # [1, H, RD]  decoupled rope queries
    c_hbm,     # [L, N, page, 1, R]  compressed latent cache (ANY)
    kr_hbm,    # [L, N, page, 1, RD] shared rope-key cache (ANY)
    o_ref,     # [1, H, R]
    c_buf,     # VMEM [2, P, page, 1, R]
    kr_buf,    # VMEM [2, P, page, 1, RD]
    sem,       # DMA semaphores [2]
    *,
    scale: float,
    block_size: int,
    pages_per_chunk: int,
):
    """MLA decode: score = q_lat·c + q_rope·k_rope, output = softmax·c.

    Same double-buffered page pipeline as _decode_kernel, but the two key
    components stream together and the value IS the latent (attention
    weights re-read c) — so each page moves R+RD bytes once, not twice.
    """
    b = pl.program_id(0)
    ctx = ctx_ref[b]
    li = li_ref[0]
    npages = pl.cdiv(ctx, block_size)
    nchunks = pl.cdiv(npages, pages_per_chunk)

    _, h, r = ql_ref.shape
    rd = qr_ref.shape[-1]
    chunk_t = pages_per_chunk * block_size

    def page_copy(chunk, slot, i, hbm, buf):
        p = jnp.minimum(chunk * pages_per_chunk + i, npages - 1)
        return pltpu.make_async_copy(
            hbm.at[li, bt_ref[b, p]], buf.at[slot, i], sem.at[slot]
        )

    def start(chunk, slot):
        for i in range(pages_per_chunk):
            page_copy(chunk, slot, i, c_hbm, c_buf).start()
            page_copy(chunk, slot, i, kr_hbm, kr_buf).start()

    def wait(chunk, slot):
        for i in range(pages_per_chunk):
            page_copy(chunk, slot, i, c_hbm, c_buf).wait()
            page_copy(chunk, slot, i, kr_hbm, kr_buf).wait()

    start(0, 0)
    ql = ql_ref[0]  # [H, R]
    qr = qr_ref[0]  # [H, RD]

    def body(ch, carry):
        m, l, acc = carry
        slot = jax.lax.rem(ch, 2)

        @pl.when(ch + 1 < nchunks)
        def _prefetch():
            start(ch + 1, jax.lax.rem(ch + 1, 2))

        wait(ch, slot)
        # upcast from the cache storage dtype (fp8 serving stores e4m3;
        # no-op for bf16) — the score dots need a uniform compute dtype
        c = c_buf[slot].reshape(chunk_t, r).astype(ql.dtype)
        kr = kr_buf[slot].reshape(chunk_t, rd).astype(ql.dtype)

        key_pos = ch * chunk_t + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk_t), 1
        )
        valid = key_pos < ctx

        s_log = (
            jax.lax.dot_general(
                ql, c, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            + jax.lax.dot_general(
                qr, kr, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        ) * scale                                        # [H, chunk_t]
        s_log = jnp.where(valid, s_log, MASK_VALUE)

        m_new = jnp.maximum(m, jnp.max(s_log, -1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p_unn = jnp.exp(s_log - m_new[:, 0:1])
        l_new = alpha * l + jnp.sum(p_unn, -1, keepdims=True)
        pv = jax.lax.dot_general(
            p_unn.astype(c.dtype), c,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                # [H, R]
        return m_new, l_new, acc * alpha[:, 0:1] + pv

    # [H, 128] lane-broadcast running stats (see _decode_kernel)
    m0 = jnp.full((h, 128), MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((h, 128), jnp.float32)
    acc0 = jnp.zeros((h, r), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nchunks, body, (m0, l0, acc0))
    l1 = l[:, 0:1]
    l1 = jnp.where(l1 == 0.0, 1.0, l1)
    o_ref[0] = (acc / l1).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("scale", "pages_per_chunk", "interpret")
)
def mla_paged_decode_attention(
    q_lat: jax.Array,        # [B, 1, H, R] latent-absorbed queries
    q_rope: jax.Array,       # [B, 1, H, RD] post-RoPE decoupled queries
    c_cache: jax.Array,      # [L, N, page, 1, R] (or 4-D single layer)
    kr_cache: jax.Array,     # [L, N, page, 1, RD]
    block_tables: jax.Array, # [B, W] int32
    context_lens: jax.Array, # [B] int32
    layer_idx: Optional[jax.Array] = None,
    scale: float = 1.0,
    pages_per_chunk: int = 8,
    interpret: bool = False,
) -> jax.Array:
    """DeepSeek MLA single-token attention over the compressed cache.

    Returns the latent output [B, 1, H, R] (caller applies W_uv). Same
    role as models/deepseek.mla_paged_attention's decode case without the
    per-layer gather: the layer is indexed inside HBM.
    """
    b, s, h, r = q_lat.shape
    assert s == 1, "decode kernel is specialized to one query token"
    rd = q_rope.shape[-1]
    if c_cache.ndim == 4:
        c_cache, kr_cache = c_cache[None], kr_cache[None]
    _, _, block_size, _, _ = c_cache.shape
    li = (
        jnp.zeros((1,), jnp.int32)
        if layer_idx is None
        else jnp.asarray(layer_idx, jnp.int32).reshape(1)
    )
    pages_per_chunk = min(pages_per_chunk, block_tables.shape[1])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, r), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec((1, h, rd), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, h, r), lambda i, *_: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM(
                (2, pages_per_chunk, block_size, 1, r), c_cache.dtype
            ),
            pltpu.VMEM(
                (2, pages_per_chunk, block_size, 1, rd), kr_cache.dtype
            ),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )

    out = pl.pallas_call(
        functools.partial(
            _mla_decode_kernel,
            scale=scale,
            block_size=block_size,
            pages_per_chunk=pages_per_chunk,
        ),
        grid_spec=grid_spec,
        out_shape=_out_struct((b, h, r), q_lat.dtype, q_lat, c_cache),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        context_lens.astype(jnp.int32),
        li,
        q_lat.reshape(b, h, r),
        q_rope.reshape(b, h, rd),
        c_cache,
        kr_cache,
    )
    return out.reshape(b, 1, h, r)


def _verify_kernel(
    bt_ref,    # scalar prefetch: block tables [B, W] (SMEM)
    ctx_ref,   # scalar prefetch: context lens [B] (incl. all S new slots)
    base_ref,  # scalar prefetch: base query position [B] (q[:, 0]'s pos)
    li_ref,    # scalar prefetch: layer index [1]
    win_ref,   # scalar prefetch: sliding window [1] (>= ctx disables)
    q_ref,     # [1, S, KVH, G, D] VMEM block
    k_hbm,     # [L, N, page, KVH, D] in HBM (ANY)
    v_hbm,
    *rest,     # ([sinks_ref [1, KVH*G] when has_sinks], o_ref, scratch...)
    scale: float,
    block_size: int,
    pages_per_chunk: int,
    softcap: float,
    s_q: int,
    has_sinks: bool = False,
):
    """Multi-token verify attention: S query tokens per row over the
    SAME single page walk — the speculative propose-verify step's
    attention reads each KV page once instead of the flash-prefill
    kernel's per-query-block passes over the table capacity.

    Same double-buffered HBM→VMEM page pipeline as ``_decode_kernel``;
    the q rows flatten (s, kvh, g) → rows and the mask adds the causal
    tail: query s sits at absolute position base + s (the flash
    kernel's affine contract — base rides as its own prefetch operand,
    so a right-padded chunk behaves exactly like flash: pad rows score
    against the bounded valid range and the caller discards them), and
    key j is visible iff j <= base + s AND j < ctx (and inside the
    sliding window).

    ``has_sinks`` (GPT-OSS): the per-head sink logit joins EVERY query
    position's softmax as a denominator-only virtual key — the [1,
    KVH*G] operand tiles across the S query rows at finalize.
    """
    if has_sinks:
        sinks_ref, o_ref, k_buf, v_buf, sem = rest
    else:
        o_ref, k_buf, v_buf, sem = rest
    b = pl.program_id(0)
    ctx = ctx_ref[b]
    base = base_ref[b]
    li = li_ref[0]
    npages = pl.cdiv(ctx, block_size)
    nchunks = pl.cdiv(npages, pages_per_chunk)
    # the earliest key ANY query can see (query 0's window lower bound)
    win_start = jnp.maximum(base + 1 - win_ref[0], 0)

    _, s, kvh, g, d = q_ref.shape
    rows = s * kvh * g
    chunk_t = pages_per_chunk * block_size
    cols = chunk_t * kvh

    def page_copy(chunk, slot, i, hbm, buf):
        p = jnp.minimum(chunk * pages_per_chunk + i, npages - 1)
        return pltpu.make_async_copy(
            hbm.at[li, bt_ref[b, p]], buf.at[slot, i], sem.at[slot]
        )

    def start(chunk, slot):
        for i in range(pages_per_chunk):
            page_copy(chunk, slot, i, k_hbm, k_buf).start()
            page_copy(chunk, slot, i, v_hbm, v_buf).start()

    def wait(chunk, slot):
        for i in range(pages_per_chunk):
            page_copy(chunk, slot, i, k_hbm, k_buf).wait()
            page_copy(chunk, slot, i, v_hbm, v_buf).wait()

    first_chunk = win_start // chunk_t
    start(first_chunk, jax.lax.rem(first_chunk, 2))
    q = q_ref[0].reshape(rows, d)  # rows ordered (s, head, group)

    col_head = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1) % kvh
    row_flat = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0)
    row_head = (row_flat % (kvh * g)) // g
    row_s = row_flat // (kvh * g)
    head_match = col_head == row_head                    # loop-invariant
    col_tok = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1) // kvh
    # per-row absolute query position (affine from the base operand)
    q_pos = base + row_s

    def body(c, carry):
        m, l, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nchunks)
        def _prefetch():
            start(c + 1, jax.lax.rem(c + 1, 2))

        wait(c, slot)
        k = k_buf[slot].reshape(cols, d).astype(q.dtype)
        v = v_buf[slot].reshape(cols, d).astype(q.dtype)

        key_pos = c * chunk_t + col_tok
        mask = (head_match
                & (key_pos <= q_pos)
                & (key_pos < ctx)
                & (key_pos > q_pos - win_ref[0]))

        s_log = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if softcap:
            s_log = softcap * jnp.tanh(s_log / softcap)
        s_log = jnp.where(mask, s_log, MASK_VALUE)

        m_cur = jnp.max(s_log, -1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        p_unn = jnp.exp(s_log - m_new[:, 0:1])
        l_new = alpha * l + jnp.sum(p_unn, -1, keepdims=True)
        pv = jax.lax.dot_general(
            p_unn.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc * alpha[:, 0:1] + pv

    m0 = jnp.full((rows, 128), MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((rows, 128), jnp.float32)
    acc0 = jnp.zeros((rows, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(first_chunk, nchunks, body, (m0, l0, acc0))
    l1 = l[:, 0:1]
    if has_sinks:
        # denominator-only virtual key, per (kvh, g) head, identical for
        # every query position: tile the [KVH*G] sink row across the S
        # query rows so row (s, kvh, g) sees sink[kvh*g] (see
        # _decode_kernel — any shared shift cancels, so the keys-only
        # running max m serves without a combined-max pass)
        sink_rows = jnp.broadcast_to(
            sinks_ref[0][None, :], (s, kvh * g)
        ).reshape(rows, 1)
        l1 = l1 + jnp.exp(sink_rows.astype(jnp.float32) - m[:, 0:1])
    l1 = jnp.where(l1 == 0.0, 1.0, l1)
    o_ref[0] = (acc / l1).astype(o_ref.dtype).reshape(s, kvh, g, d)


# largest tail the verify kernel serves: beyond it the flash-prefill
# kernel's blocked pipeline wins anyway (spec rounds are K+1 <= 17)
VERIFY_MAX_S = 32


@functools.partial(
    jax.jit,
    static_argnames=("scale", "pages_per_chunk", "interpret", "softcap"),
)
def paged_verify_attention(
    q: jax.Array,            # [B, S, H, D] (post-RoPE), S small
    k_cache: jax.Array,      # [L, N, page, KVH, D] stacked (or 4-D)
    v_cache: jax.Array,
    block_tables: jax.Array, # [B, W] int32
    base_pos: jax.Array,     # [B] int32 — absolute position of q[:, 0]
    context_lens: jax.Array, # [B] int32 (valid keys; may be < base + S)
    layer_idx: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    pages_per_chunk: int = 8,
    interpret: bool = False,
    softcap: float = 0.0,
    window=None,
    sinks=None,              # [H] per-head sink logits (GPT-OSS); None = off
) -> jax.Array:
    """S-token verify attention over the paged cache; returns
    [B, S, H, D]. The flash kernel's affine contract: query s of row b
    sits at ``base_pos[b] + s``; rows past ``context_lens`` (a padded
    chunk) produce garbage the caller discards."""
    b, s, h, d = q.shape
    assert 1 < s <= VERIFY_MAX_S, "verify kernel serves small S tails"
    if k_cache.ndim == 4:
        k_cache, v_cache = k_cache[None], v_cache[None]
    _, _, block_size, kvh, _ = k_cache.shape
    g = h // kvh
    if scale is None:
        scale = d ** -0.5
    li = (
        jnp.zeros((1,), jnp.int32)
        if layer_idx is None
        else jnp.asarray(layer_idx, jnp.int32).reshape(1)
    )
    win = (
        jnp.full((1,), jnp.int32(2**30))
        if window is None
        else jnp.asarray(window, jnp.int32).reshape(1)
    )
    pages_per_chunk = min(pages_per_chunk, block_tables.shape[1])
    qs = q.reshape(b, s, kvh, g, d)
    has_sinks = sinks is not None

    in_specs = [
        pl.BlockSpec((1, s, kvh, g, d), lambda i, *_: (i, 0, 0, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    if has_sinks:
        # [1, KVH*G] replicated to every grid step; the kernel tiles it
        # across the S query rows itself
        in_specs.append(pl.BlockSpec((1, kvh * g), lambda i, *_: (0, 0)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(b,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, s, kvh, g, d), lambda i, *_: (i, 0, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM(
                (2, pages_per_chunk, block_size, kvh, d), k_cache.dtype
            ),
            pltpu.VMEM(
                (2, pages_per_chunk, block_size, kvh, d), v_cache.dtype
            ),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )

    operands = [
        block_tables.astype(jnp.int32),
        context_lens.astype(jnp.int32),
        base_pos.astype(jnp.int32),
        li,
        win,
        qs,
        k_cache,
        v_cache,
    ]
    if has_sinks:
        operands.append(
            jnp.asarray(sinks, jnp.float32).reshape(1, kvh * g)
        )

    out = pl.pallas_call(
        functools.partial(
            _verify_kernel,
            scale=scale,
            block_size=block_size,
            pages_per_chunk=pages_per_chunk,
            softcap=softcap,
            s_q=s,
            has_sinks=has_sinks,
        ),
        grid_spec=grid_spec,
        out_shape=_out_struct((b, s, kvh, g, d), q.dtype, q, k_cache),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(*operands)
    return out.reshape(b, s, h, d)


@functools.partial(
    jax.jit,
    static_argnames=("scale", "pages_per_chunk", "interpret", "softcap"),
)
def paged_decode_attention(
    q: jax.Array,            # [B, 1, H, D] (post-RoPE)
    k_cache: jax.Array,      # [L, N, page, KVH, D] stacked (or [N, page, KVH, D])
    v_cache: jax.Array,
    block_tables: jax.Array, # [B, W] int32
    context_lens: jax.Array, # [B] int32
    layer_idx: Optional[jax.Array] = None,  # scalar int32 into L (default 0)
    scale: Optional[float] = None,
    pages_per_chunk: int = 8,
    interpret: bool = False,
    softcap: float = 0.0,    # Gemma-2: logits ← cap·tanh(logits/cap)
    window=None,             # sliding window (int or traced scalar); None = off
    sinks=None,              # [H] per-head sink logits (GPT-OSS); None = off
) -> jax.Array:
    """Single-token paged attention; returns [B, 1, H, D].

    ``window`` may be traced (Gemma-2 alternates windowed/full layers
    inside its layer scan), so it rides as a scalar-prefetch operand; the
    kernel starts its page walk at the window's first live chunk."""
    b, s, h, d = q.shape
    assert s == 1, "decode kernel is specialized to one query token"
    if k_cache.ndim == 4:
        k_cache, v_cache = k_cache[None], v_cache[None]
    _, _, block_size, kvh, _ = k_cache.shape
    g = h // kvh
    if scale is None:
        scale = d ** -0.5
    li = (
        jnp.zeros((1,), jnp.int32)
        if layer_idx is None
        else jnp.asarray(layer_idx, jnp.int32).reshape(1)
    )
    win = (
        jnp.full((1,), jnp.int32(2**30))
        if window is None
        else jnp.asarray(window, jnp.int32).reshape(1)
    )
    # fewer in-flight copies than pages in a short context wastes nothing;
    # more than the table width would index past it
    pages_per_chunk = min(pages_per_chunk, block_tables.shape[1])

    qs = q.reshape(b, kvh, g, d)
    has_sinks = sinks is not None

    in_specs = [
        pl.BlockSpec((1, kvh, g, d), lambda i, *_: (i, 0, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    if has_sinks:
        # [1, rows] replicated to every grid step; row order (kv, g)
        # matches the kernel's q flattening
        in_specs.append(pl.BlockSpec((1, kvh * g), lambda i, *_: (0, 0)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, kvh, g, d), lambda i, *_: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM(
                (2, pages_per_chunk, block_size, kvh, d), k_cache.dtype
            ),
            pltpu.VMEM(
                (2, pages_per_chunk, block_size, kvh, d), v_cache.dtype
            ),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )

    operands = [
        block_tables.astype(jnp.int32),
        context_lens.astype(jnp.int32),
        li,
        win,
        qs,
        k_cache,
        v_cache,
    ]
    if has_sinks:
        operands.append(
            jnp.asarray(sinks, jnp.float32).reshape(1, kvh * g)
        )

    out = pl.pallas_call(
        functools.partial(
            _decode_kernel,
            scale=scale,
            block_size=block_size,
            pages_per_chunk=pages_per_chunk,
            softcap=softcap,
            has_sinks=has_sinks,
        ),
        grid_spec=grid_spec,
        out_shape=_out_struct((b, kvh, g, d), q.dtype, q, k_cache),
        compiler_params=_compiler_params(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(*operands)
    return out.reshape(b, 1, h, d)
