"""jax version shims for the mesh-dependent import seams.

``jax.shard_map`` graduated from ``jax.experimental.shard_map`` (and its
``check_rep`` knob was renamed ``check_vma``) across the jax versions
this repo must run on. Importing ``jax.shard_map`` at module scope made
every mesh-dependent module — parallel/, the MLA decode dispatch, the
ICI transfer plane — fail at COLLECTION on older builds, which is how
the long-standing tier-1 ``AttributeError: module 'jax' has no
attribute 'shard_map'`` class was born. This module is the one seam
(mirroring ops/pallas_decode.py's ``_out_struct``/``_compiler_params``
shims for the Pallas API drift): resolve once, translate the kwarg, and
every caller imports ``shard_map`` from here.

Lives under ops/ (whose package __init__ is empty) rather than
parallel/ so ops/attention.py can import it without the
ops → parallel → pipeline → models → ops cycle.
"""

from __future__ import annotations

import functools

import jax
from jax import lax

if hasattr(lax, "axis_size"):
    axis_size = lax.axis_size
else:  # pre-axis_size builds: the classic psum(1) idiom (constant-folded)
    def axis_size(axis_name):
        return lax.psum(1, axis_name)


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pre-graduation builds: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    @functools.wraps(_legacy_shard_map)
    def shard_map(f=None, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:
            # functools.partial(shard_map, mesh=..., ...) decorator form
            return functools.partial(shard_map, **kwargs)
        return _legacy_shard_map(f, **kwargs)
