"""Attention over the paged KV cache.

Unified design: new K/V are always scattered into the cache first, then
queries attend over gathered cache blocks — the same code path serves
bucketed prefill (S>1, narrow KV width) and single-token decode (S=1, full
width). The XLA path below is the reference implementation; the Pallas
flash/paged kernel (ops/pallas_attention.py) replaces it on TPU where the
gather would otherwise materialize B×W×bs keys in HBM.

Replaces the role of the reference's GPU engines' paged attention (the
reference delegated to vLLM; SURVEY.md §7 "hard parts" #1).
"""

from __future__ import annotations

import contextlib
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..telemetry.registry import Counter
from .compat import shard_map

LANE = 128  # TPU vector lane width — HBM layouts tile the minor dim to this

# ---------- route observability ----------
#
# Which kernel served each program: the dispatch decision below is made
# at TRACE time (it is static per compiled specialization), so the
# counter increments once per (program, shape-bucket) compile — the
# fleet-level signal is which route each program's traces took, not a
# per-step rate. The engine registers this singleton into the runner's
# compile registry (rendered in the scheduler's scrape) and installs
# ``route_program`` as the CompileTracker's dispatch hook so records
# carry the program label.
ATTENTION_ROUTE_COUNTER = Counter(
    "dynamo_engine_attention_route_total",
    "Attention kernel route chosen at trace time per compiled program "
    "specialization, labelled program= (the engine program tracing) and "
    "route=xla|decode|verify|flash|sp_ring_kernel|sp_ring_gather",
)

_route_program = "unknown"


@contextlib.contextmanager
def route_program(name: str):
    """Label route records with the engine program being dispatched
    (installed as CompileTracker.dispatch_cm — active only while a
    tracked dispatch, and therefore its trace, is on the stack)."""
    global _route_program
    prev = _route_program
    _route_program = name
    try:
        yield
    finally:
        _route_program = prev


def record_route(route: str) -> None:
    """Stamp one route decision (called from the dispatch seams here
    and in parallel/sequence.py — trace-time Python, never traced)."""
    ATTENTION_ROUTE_COUNTER.inc(program=_route_program, route=route)


def lane_pad(d: int) -> int:
    """Smallest multiple of LANE >= d.

    KV caches are allocated with their minor (head/latent) dim padded to
    this: Mosaic requires DMA slices of HBM refs to be lane-aligned, and
    XLA pads the tiled HBM layout to 128 lanes anyway — so a head_dim-64
    cache already occupies 128 lanes physically; making the padding
    explicit costs no memory and unlocks the manual-DMA decode kernels
    (ops/pallas_decode.py). Pad lanes are kept zero (zero-padded writes)
    so padded q · padded k contributes nothing to attention scores.
    """
    return -(-d // LANE) * LANE


def _pad_minor(x: jax.Array, d: int) -> jax.Array:
    """Zero-pad the trailing dim of x up to d (no-op if already d)."""
    if x.shape[-1] == d:
        return x
    pad = [(0, 0)] * (x.ndim - 1) + [(0, d - x.shape[-1])]
    return jnp.pad(x, pad)


def scatter_kv(
    k_cache: jax.Array,  # [N_blocks, block_size, KVH, D] (one layer)
    v_cache: jax.Array,
    new_k: jax.Array,    # [B, S, KVH, D]
    new_v: jax.Array,
    slot_mapping: jax.Array,  # [B, S] flat slot index (block*bs + off); -1 → drop
) -> Tuple[jax.Array, jax.Array]:
    """Write new K/V into cache slots. Out-of-range (-1) slots are dropped.

    The two caches may have different trailing (heads, dim) — MLA stores a
    latent in "k" and the shared rope key in "v" (models/deepseek.py)."""
    n_blocks, block_size, kvh, dk = k_cache.shape
    vh, dv = v_cache.shape[-2:]
    # cast at the write (fp8 KV cache stores e4m3; no-op otherwise)
    new_k = _pad_minor(new_k, dk).astype(k_cache.dtype)
    new_v = _pad_minor(new_v, dv).astype(v_cache.dtype)
    flat_k = k_cache.reshape(n_blocks * block_size, kvh, dk)
    flat_v = v_cache.reshape(n_blocks * block_size, vh, dv)
    idx = slot_mapping.reshape(-1)
    # jax wraps negative scatter indices (-1 == last slot), so map the drop
    # sentinel to a genuinely out-of-range index for mode="drop" to act on
    idx = jnp.where(idx < 0, n_blocks * block_size, idx)
    flat_k = flat_k.at[idx].set(new_k.reshape(-1, kvh, dk), mode="drop")
    flat_v = flat_v.at[idx].set(new_v.reshape(-1, vh, dv), mode="drop")
    return (
        flat_k.reshape(n_blocks, block_size, kvh, dk),
        flat_v.reshape(n_blocks, block_size, vh, dv),
    )


def scatter_kv_stacked(
    k_all: jax.Array,  # [L, N_blocks, block_size, KVH, Dk] (stacked layers)
    v_all: jax.Array,  # [L, N_blocks, block_size, VH, Dv]
    new_k: jax.Array,  # [B, S, KVH, Dk]
    new_v: jax.Array,  # [B, S, VH, Dv]
    slot_mapping: jax.Array,  # [B, S] flat slot index (block*bs + off); -1 → drop
    layer_idx: jax.Array,     # scalar int32
) -> Tuple[jax.Array, jax.Array]:
    """Write new K/V into one layer of the *stacked* cache, in place.

    The per-layer scan used to slice the layer out (a whole-layer copy),
    scatter, and splice it back (another copy) — ~0.5 ms/layer of pure
    HBM traffic on the 1B flagship. Scattering at ``layer*N*bs + slot``
    into a flat view keeps XLA's in-place scatter on the donated carry.
    """
    l, n_blocks, block_size, kvh, dk = k_all.shape
    vh, dv = v_all.shape[-2:]
    new_k = _pad_minor(new_k, dk).astype(k_all.dtype)
    new_v = _pad_minor(new_v, dv).astype(v_all.dtype)
    idx = slot_mapping.reshape(-1)
    # drop sentinel AND per-layer overflow → past-the-end: a negative index
    # would wrap (see scatter_kv), and a positive out-of-range one would land
    # in the next layer's slab after the layer offset — both must drop
    per_layer = n_blocks * block_size
    total = l * per_layer
    flat_idx = jnp.where(
        (idx < 0) | (idx >= per_layer), total, layer_idx * per_layer + idx
    )
    flat_k = k_all.reshape(l * n_blocks * block_size, kvh, dk)
    flat_v = v_all.reshape(l * n_blocks * block_size, vh, dv)
    flat_k = flat_k.at[flat_idx].set(new_k.reshape(-1, kvh, dk), mode="drop")
    flat_v = flat_v.at[flat_idx].set(new_v.reshape(-1, vh, dv), mode="drop")
    return flat_k.reshape(k_all.shape), flat_v.reshape(v_all.shape)


def paged_attention(
    q: jax.Array,            # [B, S, H, D] (post-RoPE)
    k_cache: jax.Array,      # [N_blocks, block_size, KVH, D]
    v_cache: jax.Array,
    block_tables: jax.Array, # [B, W] block ids for each sequence
    q_positions: jax.Array,  # [B, S] absolute position of each query token
    context_lens: jax.Array, # [B] total valid tokens (incl. current) per seq
    scale: Optional[float] = None,
    softcap: float = 0.0,    # Gemma-2: logits ← cap·tanh(logits/cap)
    sliding_window=None,     # scalar (may be traced): keys within the window
    sinks=None,              # [H] per-head attention-sink logits (GPT-OSS)
) -> jax.Array:
    """Reference paged attention: gather → masked softmax → weighted sum.

    Causal semantics: query at absolute position p attends cache positions
    j where j <= p and j < context_len — and, with ``sliding_window`` w,
    j > p - w. Cache position of slot s in the gathered layout is exactly
    its sequence position (block_tables are in sequence order).

    ``sinks``: a learned per-head logit that joins the softmax as a
    virtual key contributing NO value — its only effect is the extra
    exp(sink) term in the denominator (GPT-OSS attention sinks).
    """
    b, s, h, d = q.shape
    _, block_size, kvh, _ = k_cache.shape
    w = block_tables.shape[1]
    groups = h // kvh
    if scale is None:
        scale = d ** -0.5

    # gather: [B, W, bs, KVH, D] → [B, W*bs, KVH, D]; upcast from the
    # cache storage dtype (fp8 serving) to the compute dtype
    k = k_cache[block_tables].reshape(b, w * block_size, kvh, d).astype(q.dtype)
    v = v_cache[block_tables].reshape(b, w * block_size, kvh, d).astype(q.dtype)

    # [B, S, H, D] x [B, T, KVH, D] with GQA: fold H → (KVH, G)
    qg = q.reshape(b, s, kvh, groups, d)
    logits = jnp.einsum("bskgd,btkd->bskgt", qg * scale, k)

    if softcap:
        logits = softcap * jnp.tanh(logits / softcap)

    key_pos = jnp.arange(w * block_size)[None, None, :]          # [1, 1, T]
    causal = key_pos <= q_positions[:, :, None]                   # [B, S, T]
    valid = key_pos < context_lens[:, None, None]                 # [B, 1→S, T]
    mask = causal & valid                                         # [B, S, T]
    if sliding_window is not None:
        mask &= key_pos > (q_positions[:, :, None] - sliding_window)
    mask = mask[:, :, None, None, :]                              # [B, S, 1, 1, T]
    logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)

    if sinks is not None:
        # append the sink as one extra softmax column per (kv head,
        # group), then drop its probability — the value sum is over real
        # keys only, but the denominator includes exp(sink)
        sink_col = jnp.broadcast_to(
            jnp.asarray(sinks, logits.dtype).reshape(1, 1, kvh, groups, 1),
            (b, s, kvh, groups, 1),
        )
        logits = jnp.concatenate([logits, sink_col], axis=-1)
        probs = jax.nn.softmax(
            logits.astype(jnp.float32), axis=-1
        ).astype(q.dtype)[..., :-1]
    else:
        probs = jax.nn.softmax(
            logits.astype(jnp.float32), axis=-1
        ).astype(q.dtype)
    out = jnp.einsum("bskgt,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)


def resolve_attention_impl(impl: str) -> str:
    """'auto' → pallas on TPU, xla elsewhere (pallas still testable on CPU
    via interpret=True)."""
    if impl in ("xla", "pallas"):
        return impl
    if impl != "auto":
        raise ValueError(f"unknown attention impl {impl!r}; use auto|xla|pallas")
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def attention(
    q: jax.Array,            # [B, S, H, D]
    k_cache: jax.Array,      # [N_blocks, bs, KVH, D] or stacked [L, N, bs, KVH, D]
    v_cache: jax.Array,
    block_tables: jax.Array, # [B, W]
    positions: jax.Array,    # [B, S] absolute query positions
    context_lens: jax.Array, # [B]
    impl: str = "auto",
    mesh=None,
    interpret: bool = False,
    layer_idx=None,          # required when the cache is stacked (5-D)
    scale: Optional[float] = None,  # override the head-dim default
    softcap: float = 0.0,           # Gemma-2 attention logit softcapping
    sliding_window=None,            # scalar window (int or traced); None = off
    sinks=None,                     # [H] attention-sink logits (GPT-OSS)
) -> jax.Array:
    """Paged-attention dispatch: XLA gather path or the Pallas kernels.

    ``sinks`` (GPT-OSS): a per-head logit joining every softmax as a
    virtual key with no value — both Pallas kernels fold it into their
    finalize denominator; the XLA path appends a softmax column.

    Accepts the engine's full stacked-by-layer cache plus a runtime
    ``layer_idx`` — the Pallas kernels index the layer inside HBM, so the
    per-layer scan never materializes a layer copy. Decode (S == 1) takes
    the latency-tuned kernel (pallas_decode.py); prefill takes the
    flash-pipeline kernel (pallas_attention.py), which assumes affine
    query positions (positions[:, s] == positions[:, 0] + s) — the
    scheduler's layout. With a multi-device mesh it runs under shard_map:
    batch over "dp", KV heads over "tp" (no collectives — attention is
    head/batch parallel).
    """
    stacked = k_cache.ndim == 5
    li = jnp.asarray(0 if layer_idx is None else layer_idx, jnp.int32)
    # scale from the TRUE head dim; the cache may carry lane padding
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    dk = k_cache.shape[-1]
    q = _pad_minor(q, dk)  # zero pad lanes score 0 against zero cache pad
    if resolve_attention_impl(impl) == "xla":
        if stacked:
            # index the layer through the gather itself: block id n of
            # layer li lives at flat row li*N + n. dynamic_index_in_dim
            # would materialize a full-layer copy every scan step (~2x the
            # whole cache in HBM traffic per forward); offsetting the
            # (tiny) block table is free
            l, n_blocks = k_cache.shape[:2]
            k_cache = k_cache.reshape((l * n_blocks,) + k_cache.shape[2:])
            v_cache = v_cache.reshape((l * n_blocks,) + v_cache.shape[2:])
            block_tables = block_tables + li * n_blocks
        record_route("xla")
        return paged_attention(q, k_cache, v_cache, block_tables, positions,
                               context_lens, scale=scale, softcap=softcap,
                               sliding_window=sliding_window,
                               sinks=sinks)[..., :d]

    from .pallas_attention import paged_flash_attention
    from .pallas_decode import (
        VERIFY_MAX_S,
        paged_decode_attention,
        paged_verify_attention,
    )

    import os

    # trace-time escape: lets model-level tests drive the full Pallas
    # path through jitted forwards on CPU (models don't plumb interpret)
    interpret = interpret or bool(os.environ.get("DYN_PALLAS_INTERPRET"))
    if not stacked:
        k_cache, v_cache = k_cache[None], v_cache[None]
    # the window may be a traced scalar (Gemma-2 alternates windowed/full
    # layers inside its layer scan) — it rides as a [1] operand so the
    # kernels stay compiled once across layers; None = disabled sentinel
    win = (
        jnp.full((1,), jnp.int32(2**30))
        if sliding_window is None
        else jnp.asarray(sliding_window, jnp.int32).reshape(1)
    )
    decode = q.shape[1] == 1
    has_sinks = sinks is not None
    sink_args = (sinks,) if has_sinks else ()
    # small-S tails (the speculative verify's K+1 positions; follows the
    # flash kernel's affine base_pos contract, so small custom prefill
    # buckets mask correctly too) take the fused verify kernel: ONE page
    # walk for all S queries instead of the flash kernel's per-query-
    # block passes over the table capacity. Softcap, sinks and fp8
    # caches are kernel specializations exactly like the bf16 base —
    # warmup probes the matching variant kind (ops/probe.py "verify_*")
    # before any of them may compile in-process, so a probe failure
    # falls the whole engine back to XLA rather than landing here.
    verify = 1 < q.shape[1] <= VERIFY_MAX_S
    if verify:
        record_route("verify")
        fn = functools.partial(
            paged_verify_attention, scale=scale, interpret=interpret,
            softcap=softcap,
        )
        vbase = positions[:, 0].astype(jnp.int32)
        args = (q, k_cache, v_cache, block_tables, vbase, context_lens,
                li, win) + sink_args

        def call(q, k_cache, v_cache, block_tables, vbase, context_lens,
                 li, win, *sk):
            return fn(q, k_cache, v_cache, block_tables, vbase,
                      context_lens, li, window=win,
                      sinks=sk[0] if sk else None)
    elif decode:
        record_route("decode")
        fn = functools.partial(
            paged_decode_attention, scale=scale, interpret=interpret,
            softcap=softcap,
        )
        args = (q, k_cache, v_cache, block_tables, context_lens, li,
                win) + sink_args

        def call(q, k_cache, v_cache, block_tables, context_lens, li, win,
                 *sk):
            return fn(q, k_cache, v_cache, block_tables, context_lens, li,
                      window=win, sinks=sk[0] if sk else None)
    else:
        record_route("flash")
        fn = functools.partial(
            paged_flash_attention, scale=scale, interpret=interpret,
            softcap=softcap,
        )
        base_pos = positions[:, 0].astype(jnp.int32)
        args = (q, k_cache, v_cache, block_tables, base_pos, context_lens,
                li, win) + sink_args

        def call(q, k_cache, v_cache, block_tables, base_pos, context_lens,
                 li, win, *sk):
            return fn(q, k_cache, v_cache, block_tables, base_pos,
                      context_lens, li, window=win,
                      sinks=sk[0] if sk else None)
    if mesh is not None and mesh.size > 1:
        # batch shards over dp only when divisible — the scheduler prefills
        # with B=1, which each dp group then computes redundantly (decode,
        # where B = max_batch_size, shards)
        dp = "dp" if q.shape[0] % mesh.shape.get("dp", 1) == 0 else None
        in_specs = [
            P(dp, None, "tp", None),           # q [B, S, H, D]
            P(None, None, None, "tp", None),   # k_cache [L, N, bs, KVH, D]
            P(None, None, None, "tp", None),   # v_cache
            P(dp, None),                       # block_tables
        ]
        if not decode:
            in_specs.append(P(dp))             # base_pos (flash + verify)
        in_specs.extend([P(dp), P(), P()])     # context_lens, layer_idx, win
        if has_sinks:
            in_specs.append(P("tp"))           # sinks follow the head shard
        call = shard_map(
            call,
            mesh=mesh,
            in_specs=tuple(in_specs),
            out_specs=P(dp, None, "tp", None),
            check_vma=False,  # pallas out_shape carries no vma annotation
        )
    return call(*args)[..., :d]


def prefill_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, KVH, D]
    v: jax.Array,
    valid_lens: jax.Array,  # [B] number of real (non-pad) tokens
    scale: Optional[float] = None,
) -> jax.Array:
    """Dense causal self-attention for prefill without cache reads (used when
    the whole context is the in-flight prompt — no prefix-cache hit)."""
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    if scale is None:
        scale = d ** -0.5
    qg = q.reshape(b, s, kvh, groups, d)
    logits = jnp.einsum("bskgd,btkd->bskgt", qg * scale, k)
    q_pos = jnp.arange(s)[None, :, None]
    k_pos = jnp.arange(s)[None, None, :]
    mask = (k_pos <= q_pos) & (k_pos < valid_lens[:, None, None])
    logits = jnp.where(mask[:, :, None, None, :], logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bskgt,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)
