"""Attention over the paged KV cache.

Unified design: new K/V are always scattered into the cache first, then
queries attend over gathered cache blocks — the same code path serves
bucketed prefill (S>1, narrow KV width) and single-token decode (S=1, full
width). The XLA path below is the reference implementation; the Pallas
flash/paged kernel (ops/pallas_attention.py) replaces it on TPU where the
gather would otherwise materialize B×W×bs keys in HBM.

Replaces the role of the reference's GPU engines' paged attention (the
reference delegated to vLLM; SURVEY.md §7 "hard parts" #1).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def scatter_kv(
    k_cache: jax.Array,  # [N_blocks, block_size, KVH, D] (one layer)
    v_cache: jax.Array,
    new_k: jax.Array,    # [B, S, KVH, D]
    new_v: jax.Array,
    slot_mapping: jax.Array,  # [B, S] flat slot index (block*bs + off); -1 → drop
) -> Tuple[jax.Array, jax.Array]:
    """Write new K/V into cache slots. Out-of-range (-1) slots are dropped.

    The two caches may have different trailing (heads, dim) — MLA stores a
    latent in "k" and the shared rope key in "v" (models/deepseek.py)."""
    n_blocks, block_size, kvh, dk = k_cache.shape
    vh, dv = v_cache.shape[-2:]
    flat_k = k_cache.reshape(n_blocks * block_size, kvh, dk)
    flat_v = v_cache.reshape(n_blocks * block_size, vh, dv)
    idx = slot_mapping.reshape(-1)
    flat_k = flat_k.at[idx].set(new_k.reshape(-1, kvh, dk), mode="drop")
    flat_v = flat_v.at[idx].set(new_v.reshape(-1, vh, dv), mode="drop")
    return (
        flat_k.reshape(n_blocks, block_size, kvh, dk),
        flat_v.reshape(n_blocks, block_size, vh, dv),
    )


def paged_attention(
    q: jax.Array,            # [B, S, H, D] (post-RoPE)
    k_cache: jax.Array,      # [N_blocks, block_size, KVH, D]
    v_cache: jax.Array,
    block_tables: jax.Array, # [B, W] block ids for each sequence
    q_positions: jax.Array,  # [B, S] absolute position of each query token
    context_lens: jax.Array, # [B] total valid tokens (incl. current) per seq
    scale: Optional[float] = None,
) -> jax.Array:
    """Reference paged attention: gather → masked softmax → weighted sum.

    Causal semantics: query at absolute position p attends cache positions
    j where j <= p and j < context_len. Cache position of slot s in the
    gathered layout is exactly its sequence position (block_tables are in
    sequence order).
    """
    b, s, h, d = q.shape
    _, block_size, kvh, _ = k_cache.shape
    w = block_tables.shape[1]
    groups = h // kvh
    if scale is None:
        scale = d ** -0.5

    # gather: [B, W, bs, KVH, D] → [B, W*bs, KVH, D]
    k = k_cache[block_tables].reshape(b, w * block_size, kvh, d)
    v = v_cache[block_tables].reshape(b, w * block_size, kvh, d)

    # [B, S, H, D] x [B, T, KVH, D] with GQA: fold H → (KVH, G)
    qg = q.reshape(b, s, kvh, groups, d)
    logits = jnp.einsum("bskgd,btkd->bskgt", qg * scale, k)

    key_pos = jnp.arange(w * block_size)[None, None, :]          # [1, 1, T]
    causal = key_pos <= q_positions[:, :, None]                   # [B, S, T]
    valid = key_pos < context_lens[:, None, None]                 # [B, 1→S, T]
    mask = (causal & valid)[:, :, None, None, :]                  # [B, S, 1, 1, T]
    logits = jnp.where(mask, logits, jnp.finfo(logits.dtype).min)

    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bskgt,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)


def resolve_attention_impl(impl: str) -> str:
    """'auto' → pallas on TPU, xla elsewhere (pallas still testable on CPU
    via interpret=True)."""
    if impl in ("xla", "pallas"):
        return impl
    if impl != "auto":
        raise ValueError(f"unknown attention impl {impl!r}; use auto|xla|pallas")
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def attention(
    q: jax.Array,            # [B, S, H, D]
    k_cache: jax.Array,      # [N_blocks, bs, KVH, D]
    v_cache: jax.Array,
    block_tables: jax.Array, # [B, W]
    positions: jax.Array,    # [B, S] absolute query positions
    context_lens: jax.Array, # [B]
    impl: str = "auto",
    mesh=None,
    interpret: bool = False,
) -> jax.Array:
    """Paged-attention dispatch: XLA gather path or the Pallas kernel.

    The Pallas path assumes affine query positions (positions[:, s] ==
    positions[:, 0] + s for real tokens) — the scheduler's layout. With a
    multi-device mesh it runs under shard_map: batch over "dp", KV heads
    over "tp" (no collectives — attention is head/batch parallel).
    """
    if resolve_attention_impl(impl) == "xla":
        return paged_attention(q, k_cache, v_cache, block_tables, positions,
                               context_lens)

    from .pallas_attention import paged_flash_attention

    fn = functools.partial(paged_flash_attention, interpret=interpret)
    base_pos = positions[:, 0].astype(jnp.int32)
    if mesh is not None and mesh.size > 1:
        # batch shards over dp only when divisible — the scheduler prefills
        # with B=1, which each dp group then computes redundantly (decode,
        # where B = max_batch_size, shards)
        dp = "dp" if q.shape[0] % mesh.shape.get("dp", 1) == 0 else None
        fn = jax.shard_map(
            fn,
            mesh=mesh,
            in_specs=(
                P(dp, None, "tp", None),     # q [B, S, H, D]
                P(None, None, "tp", None),   # k_cache
                P(None, None, "tp", None),   # v_cache
                P(dp, None),                 # block_tables
                P(dp),                       # base_pos
                P(dp),                       # context_lens
            ),
            out_specs=P(dp, None, "tp", None),
            check_vma=False,  # pallas out_shape carries no vma annotation
        )
    return fn(q, k_cache, v_cache, block_tables, base_pos, context_lens)


def prefill_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, KVH, D]
    v: jax.Array,
    valid_lens: jax.Array,  # [B] number of real (non-pad) tokens
    scale: Optional[float] = None,
) -> jax.Array:
    """Dense causal self-attention for prefill without cache reads (used when
    the whole context is the in-flight prompt — no prefix-cache hit)."""
    b, s, h, d = q.shape
    kvh = k.shape[2]
    groups = h // kvh
    if scale is None:
        scale = d ** -0.5
    qg = q.reshape(b, s, kvh, groups, d)
    logits = jnp.einsum("bskgd,btkd->bskgt", qg * scale, k)
    q_pos = jnp.arange(s)[None, :, None]
    k_pos = jnp.arange(s)[None, None, :]
    mask = (k_pos <= q_pos) & (k_pos < valid_lens[:, None, None])
    logits = jnp.where(mask[:, :, None, None, :], logits, jnp.finfo(logits.dtype).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bskgt,btkd->bskgd", probs, v)
    return out.reshape(b, s, h, d)
