"""Pallas TPU paged-prefix partials for sequence-parallel prefill.

The SP chunk ladder (parallel/sequence.sp_chunk_attention) folds two key
sources into one softmax: the chunk's fresh K/V (rotated around the sp
ring) and the committed prefix already living in the paged KV cache. The
XLA formulation GATHERS the whole prefix — ``kc[block_tables]`` builds a
``[1, W·bs, KVH, D]`` array per layer before the sharding constraint can
split it, so per-device prefill memory scales with the full context and
the 128k ladder is gather-bound, not attention-bound.

This kernel is the other half of the kernelized path: each sp device
computes online-softmax PARTIALS (unnormalized accumulator ``acc``,
running max ``m``, running sum ``l``) of its local query shard against
the paged prefix, reading pages straight from HBM with the same
double-buffered ``make_async_copy`` walk as pallas_decode.py — the cache
is replicated over sp (only tp shards KV heads), so every device walks
its local copy and per-device memory is O(pages in flight), not
O(gathered prefix). The caller merges these partials with the ring
pass's (parallel/ring_attention._ring_partials) and normalizes once.

No softcap/sinks variants: the engine's SP gate only admits llama-family
dense GQA trunks (engine/model_runner._build_sp_prefill), which use
neither. fp8 caches upcast after the DMA exactly like the decode kernel.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_decode import MASK_VALUE, _compiler_params, _out_struct


def _prefix_kernel(
    bt_ref,    # scalar prefetch: block tables [B, W] (SMEM)
    pfx_ref,   # scalar prefetch: prefix length [1] (keys at pos < pfx live)
    li_ref,    # scalar prefetch: layer index [1]
    q_ref,     # [1, S, KVH, G, D] VMEM block (the device's query shard)
    k_hbm,     # [L, N, page, KVH, D] in HBM (ANY)
    v_hbm,
    acc_ref,   # [1, S, KVH, G, D] f32 — UNNORMALIZED accumulator
    m_ref,     # [1, rows, 128] f32 lane-broadcast running max
    l_ref,     # [1, rows, 128] f32 lane-broadcast running sum
    k_buf,
    v_buf,
    sem,
    *,
    scale: float,
    block_size: int,
    pages_per_chunk: int,
):
    """One grid step = one batch row; the fori_loop walks ONLY the pages
    holding committed-prefix keys (pos < prefix_len).

    Same GQA head-flattening trick as ``_decode_kernel``: the chunk KV
    flattens to [chunk_t·KVH, D], one MXU dot pair scores every query
    row against every (token, head) column, and iota masks kill
    cross-head and out-of-prefix columns. No causal term: every prefix
    key precedes every chunk query by construction (pos < prefix_len <=
    chunk positions) — pad query rows are zeroed by the CALLER at merge
    (their ring partials are already empty, so zeroed prefix partials
    make the whole row 0).

    A zero-length prefix (the prompt's first chunk) issues no DMA at
    all and returns empty partials (m = MASK_VALUE, l = 0, acc = 0).
    """
    b = pl.program_id(0)
    pfx = pfx_ref[0]
    li = li_ref[0]
    npages = pl.cdiv(pfx, block_size)          # 0 when the prefix is empty
    nchunks = pl.cdiv(npages, pages_per_chunk)

    _, s, kvh, g, d = q_ref.shape
    rows = s * kvh * g
    chunk_t = pages_per_chunk * block_size
    cols = chunk_t * kvh

    def page_copy(chunk, slot, i, hbm, buf):
        # pages past the live range duplicate the last live page — their
        # key positions land >= pfx and the mask kills them. max() guards
        # the npages == 0 case (nothing starts then, but the index must
        # still be in range at trace time).
        p = jnp.maximum(
            jnp.minimum(chunk * pages_per_chunk + i, npages - 1), 0
        )
        return pltpu.make_async_copy(
            hbm.at[li, bt_ref[b, p]], buf.at[slot, i], sem.at[slot]
        )

    def start(chunk, slot):
        for i in range(pages_per_chunk):
            page_copy(chunk, slot, i, k_hbm, k_buf).start()
            page_copy(chunk, slot, i, v_hbm, v_buf).start()

    def wait(chunk, slot):
        for i in range(pages_per_chunk):
            page_copy(chunk, slot, i, k_hbm, k_buf).wait()
            page_copy(chunk, slot, i, v_hbm, v_buf).wait()

    @pl.when(nchunks > 0)
    def _warmup():
        start(0, 0)

    q = q_ref[0].reshape(rows, d)  # rows ordered (s, head, group)

    col_head = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1) % kvh
    row_head = (
        jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 0) % (kvh * g)
    ) // g
    head_match = col_head == row_head                    # loop-invariant
    col_tok = jax.lax.broadcasted_iota(jnp.int32, (rows, cols), 1) // kvh

    def body(c, carry):
        m, l, acc = carry                                 # [rows,128]x2, [rows,D]
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nchunks)
        def _prefetch():
            start(c + 1, jax.lax.rem(c + 1, 2))

        wait(c, slot)
        # upcast from the cache storage dtype (fp8 serving stores e4m3)
        k = k_buf[slot].reshape(cols, d).astype(q.dtype)
        v = v_buf[slot].reshape(cols, d).astype(q.dtype)

        key_pos = c * chunk_t + col_tok
        mask = head_match & (key_pos < pfx)

        s_log = jax.lax.dot_general(
            q, k,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                         # [rows, cols]
        s_log = jnp.where(mask, s_log, MASK_VALUE)

        m_cur = jnp.max(s_log, -1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        p_unn = jnp.exp(s_log - m_new[:, 0:1])
        l_new = alpha * l + jnp.sum(p_unn, -1, keepdims=True)
        pv = jax.lax.dot_general(
            p_unn.astype(v.dtype), v,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc * alpha[:, 0:1] + pv

    m0 = jnp.full((rows, 128), MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((rows, 128), jnp.float32)
    acc0 = jnp.zeros((rows, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nchunks, body, (m0, l0, acc0))
    # NO normalization — the caller merges with the ring partials first
    acc_ref[0] = acc.reshape(s, kvh, g, d)
    m_ref[0] = m
    l_ref[0] = l


@functools.partial(
    jax.jit, static_argnames=("scale", "pages_per_chunk", "interpret")
)
def paged_prefix_attention_partials(
    q: jax.Array,            # [B, S, H, D] local query shard (post-RoPE)
    k_cache: jax.Array,      # [L, N, page, KVH, Dpad] stacked (or 4-D)
    v_cache: jax.Array,
    block_tables: jax.Array, # [B, W] int32
    prefix_len: jax.Array,   # scalar int32 — keys at pos < prefix_len live
    layer_idx: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    pages_per_chunk: int = 8,
    interpret: bool = False,
):
    """Online-softmax partials of ``q`` against the committed paged
    prefix (cache positions ``< prefix_len``), read page-by-page from
    HBM. Returns ``(acc, m, l)`` with ``acc`` [B, S, KVH, G, D] f32
    unnormalized, ``m``/``l`` [B, S, KVH, G] f32 — merge with another
    key source's partials, then divide by the combined ``l``.

    Pad query rows (the chunk tail) produce partials against the whole
    prefix; the caller masks their ``l``/``acc`` to zero at merge.
    """
    b, s, h, d = q.shape
    if k_cache.ndim == 4:
        k_cache, v_cache = k_cache[None], v_cache[None]
    _, _, block_size, kvh, dk = k_cache.shape
    g = h // kvh
    if scale is None:
        scale = d ** -0.5
    if d != dk:
        # zero pad lanes score 0 against the cache's zeroed pad lanes
        q = jnp.pad(q, [(0, 0)] * 3 + [(0, dk - d)])
    li = (
        jnp.zeros((1,), jnp.int32)
        if layer_idx is None
        else jnp.asarray(layer_idx, jnp.int32).reshape(1)
    )
    pfx = jnp.asarray(prefix_len, jnp.int32).reshape(1)
    pages_per_chunk = min(pages_per_chunk, block_tables.shape[1])
    qs = q.reshape(b, s, kvh, g, dk)
    rows = s * kvh * g

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, s, kvh, g, dk), lambda i, *_: (i, 0, 0, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, s, kvh, g, dk), lambda i, *_: (i, 0, 0, 0, 0)
            ),
            pl.BlockSpec((1, rows, 128), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec((1, rows, 128), lambda i, *_: (i, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM(
                (2, pages_per_chunk, block_size, kvh, dk), k_cache.dtype
            ),
            pltpu.VMEM(
                (2, pages_per_chunk, block_size, kvh, dk), v_cache.dtype
            ),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )

    acc, m, l = pl.pallas_call(
        functools.partial(
            _prefix_kernel,
            scale=scale,
            block_size=block_size,
            pages_per_chunk=pages_per_chunk,
        ),
        grid_spec=grid_spec,
        out_shape=[
            _out_struct((b, s, kvh, g, dk), jnp.float32, q, k_cache),
            _out_struct((b, rows, 128), jnp.float32, q, k_cache),
            _out_struct((b, rows, 128), jnp.float32, q, k_cache),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel",),
        ),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        pfx,
        li,
        qs,
        k_cache,
        v_cache,
    )
    ml = m[:, :, 0].reshape(b, s, kvh, g)
    ll = l[:, :, 0].reshape(b, s, kvh, g)
    return acc[..., :d], ml, ll
