"""Subprocess compile probes for the Pallas kernels.

Why a subprocess: a pathological Mosaic compile can HANG rather than fail
(observed on shared-compile-service TPU hosts, where one wedged compile
then blocks every later backend init on the machine). An in-process
try/except around warmup catches failures but not hangs, so any *first*
compile of a Pallas kernel on a given host happens in a child process
with a hard timeout — on timeout or failure the engine falls back to the
XLA attention path and serving never wedges.

One child probes ALL requested kernels in a single JAX/backend init
(cold backend init dominates probe latency). Hosts whose TPU runtime is
process-exclusive (the child cannot acquire the device while the serving
process holds it) are detected from the child's stderr and reported as
*inconclusive* — the engine then proceeds with its normal in-process
compile under try/except, because on such hosts a child can never
compile anything and there is no shared compile service to wedge.

Reference analog: the startup capture/warmup sweeps the GPU engines run
before serving traffic (SURVEY.md §2.4); same contract, plus hang
isolation that CUDA toolchains don't need but shared TPU compile relays
do.

Used by ``bench.py`` (probe before the full-model attempt) and by
``ModelRunner.warmup`` (probe before any in-process Pallas compile).
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
from typing import Dict, Iterable, Optional

logger = logging.getLogger(__name__)

# in-process memo: kind -> True | False | None (None = inconclusive).
# One probe per process is enough — the result can't change under us,
# and warmup may run once per engine instance.
_PROBE_CACHE: Dict[str, Optional[bool]] = {}

# child-stderr markers meaning "the TPU is held by another process", not
# "the kernel is broken" — the probe is then inconclusive, not a failure
_EXCLUSIVE_DEVICE_MARKERS = (
    "already in use",
    "device or resource busy",
    "failed to open libtpu",
    "unable to acquire",
)

_PROBE_SRC = r"""
import sys

import jax
import jax.numpy as jnp
import numpy as np


def probe_decode():
    from dynamo_tpu.ops.pallas_decode import paged_decode_attention

    l, n, page, kvh, d, b, w = 2, 16, 16, 2, 128, 2, 4
    k = jnp.zeros((l, n, page, kvh, d), jnp.bfloat16)
    v = jnp.zeros((l, n, page, kvh, d), jnp.bfloat16)
    q = jnp.ones((b, 1, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    ctx = jnp.asarray([17, 33], jnp.int32)
    np.asarray(paged_decode_attention(q, k, v, bt, ctx, jnp.asarray(1, jnp.int32)))


def probe_decode_windowed():
    # windowed + softcapped variant (Gemma-2/Mistral-class configs): a
    # different static specialization, so its Mosaic compile needs its
    # own probe — but ONLY engines whose model uses it pay for it
    from dynamo_tpu.ops.pallas_decode import paged_decode_attention

    l, n, page, kvh, d, b, w = 2, 16, 16, 2, 128, 2, 4
    k = jnp.zeros((l, n, page, kvh, d), jnp.bfloat16)
    v = jnp.zeros((l, n, page, kvh, d), jnp.bfloat16)
    q = jnp.ones((b, 1, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    ctx = jnp.asarray([17, 33], jnp.int32)
    np.asarray(paged_decode_attention(
        q, k, v, bt, ctx, jnp.asarray(1, jnp.int32),
        softcap=50.0, window=jnp.asarray(16, jnp.int32),
    ))


def _probe_verify(dtype_name, softcap=False, sinks=False):
    # the S-token verify kernel (speculative propose-verify rounds):
    # its own Mosaic specialization — one page walk for all S queries.
    # softcap / sinks / fp8 are further static specializations, probed
    # only for the configs that select them (mirrors decode/prefill)
    from dynamo_tpu.ops.pallas_decode import paged_verify_attention

    l, n, page, kvh, d, b, w, s = 2, 16, 16, 2, 128, 2, 4, 4
    dt = getattr(jnp, dtype_name)
    k = jnp.zeros((l, n, page, kvh, d), dt)
    v = jnp.zeros((l, n, page, kvh, d), dt)
    q = jnp.ones((b, s, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    ctx = jnp.asarray([17, 33], jnp.int32)
    base = ctx - s
    kw = {}
    if softcap:
        kw["softcap"] = 50.0
    if sinks:
        kw["sinks"] = jnp.ones((4,), jnp.float32)
        kw["window"] = jnp.asarray(16, jnp.int32)
    np.asarray(paged_verify_attention(
        q, k, v, bt, base, ctx, jnp.asarray(1, jnp.int32), **kw
    ))


def probe_prefill():
    from dynamo_tpu.ops.pallas_attention import paged_flash_attention

    l, n, page, kvh, d, b, w, s = 2, 16, 16, 2, 128, 1, 8, 128
    k = jnp.zeros((l, n, page, kvh, d), jnp.bfloat16)
    v = jnp.zeros((l, n, page, kvh, d), jnp.bfloat16)
    q = jnp.ones((b, s, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    base = jnp.zeros((b,), jnp.int32)
    ctx = jnp.asarray([s], jnp.int32)
    np.asarray(paged_flash_attention(q, k, v, bt, base, ctx, jnp.asarray(0, jnp.int32)))


def probe_prefill_windowed():
    from dynamo_tpu.ops.pallas_attention import paged_flash_attention

    l, n, page, kvh, d, b, w, s = 2, 16, 16, 2, 128, 1, 8, 128
    k = jnp.zeros((l, n, page, kvh, d), jnp.bfloat16)
    v = jnp.zeros((l, n, page, kvh, d), jnp.bfloat16)
    q = jnp.ones((b, s, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    base = jnp.zeros((b,), jnp.int32)
    ctx = jnp.asarray([s], jnp.int32)
    np.asarray(paged_flash_attention(
        q, k, v, bt, base, ctx, jnp.asarray(0, jnp.int32),
        softcap=50.0, window=jnp.asarray(48, jnp.int32),
    ))


def probe_mla_decode():
    from dynamo_tpu.ops.pallas_decode import mla_paged_decode_attention

    l, n, page, r, rd, b, w, h = 2, 16, 16, 128, 128, 2, 4, 4
    c = jnp.zeros((l, n, page, 1, r), jnp.bfloat16)
    kr = jnp.zeros((l, n, page, 1, rd), jnp.bfloat16)
    ql = jnp.ones((b, 1, h, r), jnp.bfloat16)
    qr = jnp.ones((b, 1, h, rd), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    ctx = jnp.asarray([17, 33], jnp.int32)
    np.asarray(
        mla_paged_decode_attention(ql, qr, c, kr, bt, ctx, jnp.asarray(1, jnp.int32))
    )


def probe_decode_fp8():
    # fp8 KV serving: the cache rides as float8_e4m3fn and the kernel
    # upcasts after the DMA — a distinct Mosaic specialization
    from dynamo_tpu.ops.pallas_decode import paged_decode_attention

    l, n, page, kvh, d, b, w = 2, 16, 16, 2, 128, 2, 4
    k = jnp.zeros((l, n, page, kvh, d), jnp.float8_e4m3fn)
    v = jnp.zeros((l, n, page, kvh, d), jnp.float8_e4m3fn)
    q = jnp.ones((b, 1, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    ctx = jnp.asarray([17, 33], jnp.int32)
    np.asarray(paged_decode_attention(q, k, v, bt, ctx, jnp.asarray(1, jnp.int32)))


def probe_mla_decode_fp8():
    # fp8 latent cache (MLA x fp8 serving): distinct Mosaic
    # specialization of the MLA decode kernel (upcast after the DMA)
    from dynamo_tpu.ops.pallas_decode import mla_paged_decode_attention

    l, n, page, r, rd, b, w, h = 2, 16, 16, 128, 128, 2, 4, 4
    c = jnp.zeros((l, n, page, 1, r), jnp.float8_e4m3fn)
    kr = jnp.zeros((l, n, page, 1, rd), jnp.float8_e4m3fn)
    ql = jnp.ones((b, 1, h, r), jnp.bfloat16)
    qr = jnp.ones((b, 1, h, rd), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    ctx = jnp.asarray([17, 33], jnp.int32)
    np.asarray(
        mla_paged_decode_attention(ql, qr, c, kr, bt, ctx, jnp.asarray(1, jnp.int32))
    )


def probe_prefill_fp8():
    from dynamo_tpu.ops.pallas_attention import paged_flash_attention

    l, n, page, kvh, d, b, w, s = 2, 16, 16, 2, 128, 1, 8, 128
    k = jnp.zeros((l, n, page, kvh, d), jnp.float8_e4m3fn)
    v = jnp.zeros((l, n, page, kvh, d), jnp.float8_e4m3fn)
    q = jnp.ones((b, s, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    base = jnp.zeros((b,), jnp.int32)
    ctx = jnp.asarray([s], jnp.int32)
    np.asarray(paged_flash_attention(q, k, v, bt, base, ctx, jnp.asarray(0, jnp.int32)))


def probe_decode_windowed_fp8():
    # softcap x fp8 cache: what a Gemma-2-class model with
    # kv_cache_dtype=fp8 actually compiles (softcap is a static
    # specialization AND the dtype is — neither probe alone covers it)
    from dynamo_tpu.ops.pallas_decode import paged_decode_attention

    l, n, page, kvh, d, b, w = 2, 16, 16, 2, 128, 2, 4
    k = jnp.zeros((l, n, page, kvh, d), jnp.float8_e4m3fn)
    v = jnp.zeros((l, n, page, kvh, d), jnp.float8_e4m3fn)
    q = jnp.ones((b, 1, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    ctx = jnp.asarray([17, 33], jnp.int32)
    np.asarray(paged_decode_attention(
        q, k, v, bt, ctx, jnp.asarray(1, jnp.int32),
        softcap=50.0, window=jnp.asarray(16, jnp.int32),
    ))


def probe_prefill_windowed_fp8():
    from dynamo_tpu.ops.pallas_attention import paged_flash_attention

    l, n, page, kvh, d, b, w, s = 2, 16, 16, 2, 128, 1, 8, 128
    k = jnp.zeros((l, n, page, kvh, d), jnp.float8_e4m3fn)
    v = jnp.zeros((l, n, page, kvh, d), jnp.float8_e4m3fn)
    q = jnp.ones((b, s, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    base = jnp.zeros((b,), jnp.int32)
    ctx = jnp.asarray([s], jnp.int32)
    np.asarray(paged_flash_attention(
        q, k, v, bt, base, ctx, jnp.asarray(0, jnp.int32),
        softcap=50.0, window=jnp.asarray(48, jnp.int32),
    ))


def _probe_decode_sinks(dtype_name):
    # attention sinks (GPT-OSS): has_sinks is a static specialization;
    # the probe also exercises the windowed runtime path (the family
    # alternates windowed layers)
    from dynamo_tpu.ops.pallas_decode import paged_decode_attention

    l, n, page, kvh, d, b, w = 2, 16, 16, 2, 128, 2, 4
    dt = getattr(jnp, dtype_name)
    k = jnp.zeros((l, n, page, kvh, d), dt)
    v = jnp.zeros((l, n, page, kvh, d), dt)
    q = jnp.ones((b, 1, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    ctx = jnp.asarray([17, 33], jnp.int32)
    np.asarray(paged_decode_attention(
        q, k, v, bt, ctx, jnp.asarray(1, jnp.int32),
        window=jnp.asarray(16, jnp.int32),
        sinks=jnp.ones((4,), jnp.float32),
    ))


def _probe_prefill_sinks(dtype_name):
    from dynamo_tpu.ops.pallas_attention import paged_flash_attention

    l, n, page, kvh, d, b, w, s = 2, 16, 16, 2, 128, 1, 8, 128
    dt = getattr(jnp, dtype_name)
    k = jnp.zeros((l, n, page, kvh, d), dt)
    v = jnp.zeros((l, n, page, kvh, d), dt)
    q = jnp.ones((b, s, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    base = jnp.zeros((b,), jnp.int32)
    ctx = jnp.asarray([s], jnp.int32)
    np.asarray(paged_flash_attention(
        q, k, v, bt, base, ctx, jnp.asarray(0, jnp.int32),
        window=jnp.asarray(48, jnp.int32),
        sinks=jnp.ones((4,), jnp.float32),
    ))


def probe_sp_prefill():
    # the SP ring-prefill's paged prefix walk (ops/pallas_sp.py): reads
    # the committed prefix page-by-page from the HBM-resident cache via
    # double-buffered DMA — its own Mosaic specialization
    from dynamo_tpu.ops.pallas_sp import paged_prefix_attention_partials

    l, n, page, kvh, d, b, w, s = 2, 16, 16, 2, 128, 1, 4, 128
    k = jnp.zeros((l, n, page, kvh, d), jnp.bfloat16)
    v = jnp.zeros((l, n, page, kvh, d), jnp.bfloat16)
    q = jnp.ones((b, s, 4, d), jnp.bfloat16)
    bt = jnp.asarray(np.arange(b * w).reshape(b, w) % n, jnp.int32)
    acc, m, lse = paged_prefix_attention_partials(
        q, k, v, bt, jnp.asarray(40, jnp.int32), jnp.asarray(1, jnp.int32)
    )
    np.asarray(acc), np.asarray(m), np.asarray(lse)


def probe_epilogue():
    # the fused sampling epilogue (ops/pallas_epilogue.py): compile the
    # static variants the serving programs use — the plain tail with the
    # aliased in-kernel count commit (bursts), the unaliased form (the
    # batched prefill step), and the finish-fused chained-burst tail
    from dynamo_tpu.engine.sampling import (
        STOP_ID_WIDTH, STOP_SEQ_WIDTH, SUFFIX_RING_W,
    )
    from dynamo_tpu.ops.pallas_epilogue import fused_sampling_epilogue

    b, v, ns = 2, 256, 4
    logits = jnp.ones((b, v), jnp.float32)
    gum = jnp.zeros((b, v), jnp.float32)
    scalars = (
        jnp.ones((b,), jnp.float32), jnp.zeros((b,), jnp.int32),
        jnp.ones((b,), jnp.float32), jnp.zeros((b,), jnp.float32),
        jnp.zeros((b,), jnp.float32), jnp.zeros((b,), jnp.float32),
        jnp.ones((b,), jnp.float32),
    )
    counts = jnp.zeros((ns, v), jnp.int32)
    seen = jnp.zeros((ns, v), jnp.bool_)
    bias = jnp.zeros((ns, v), jnp.float32)
    slots = jnp.arange(b, dtype=jnp.int32)
    commit = jnp.ones((b,), jnp.bool_)
    for alias in (True, False):
        np.asarray(fused_sampling_epilogue(
            logits, gum, scalars, counts, seen, bias, slots, commit,
            max_model_len=64, alias_counts=alias,
        )[0])
    fin = (
        jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
        jnp.zeros((b,), jnp.int32), jnp.full((b,), 32, jnp.int32),
        jnp.full((b, STOP_ID_WIDTH), -1, jnp.int32),
        jnp.full((b, SUFFIX_RING_W), -1, jnp.int32),
        jnp.zeros((b, STOP_SEQ_WIDTH), jnp.uint32),
        jnp.zeros((b, STOP_SEQ_WIDTH), jnp.int32),
    )
    np.asarray(fused_sampling_epilogue(
        logits, gum, scalars, counts, seen, bias, slots, commit,
        extra_bias=jnp.zeros((b, v), jnp.float32), finish=fin,
        max_model_len=64,
    )[0])


PROBES = {
    "decode": probe_decode,
    "decode_windowed": probe_decode_windowed,
    "decode_fp8": probe_decode_fp8,
    "decode_windowed_fp8": probe_decode_windowed_fp8,
    "decode_sinks": lambda: _probe_decode_sinks("bfloat16"),
    "decode_sinks_fp8": lambda: _probe_decode_sinks("float8_e4m3fn"),
    "prefill": probe_prefill,
    "prefill_windowed": probe_prefill_windowed,
    "prefill_fp8": probe_prefill_fp8,
    "prefill_windowed_fp8": probe_prefill_windowed_fp8,
    "prefill_sinks": lambda: _probe_prefill_sinks("bfloat16"),
    "prefill_sinks_fp8": lambda: _probe_prefill_sinks("float8_e4m3fn"),
    "mla_decode": probe_mla_decode,
    "mla_decode_fp8": probe_mla_decode_fp8,
    "verify": lambda: _probe_verify("bfloat16"),
    "verify_fp8": lambda: _probe_verify("float8_e4m3fn"),
    "verify_softcap": lambda: _probe_verify("bfloat16", softcap=True),
    "verify_softcap_fp8": lambda: _probe_verify(
        "float8_e4m3fn", softcap=True),
    "verify_sinks": lambda: _probe_verify("bfloat16", sinks=True),
    "verify_sinks_fp8": lambda: _probe_verify(
        "float8_e4m3fn", sinks=True),
    "sp_prefill": probe_sp_prefill,
    "epilogue": probe_epilogue,
}
for kind in sys.argv[1:]:
    PROBES[kind]()
    # flush per kind: if a later kernel hangs/crashes the child, the
    # parent still credits the ones that finished
    print("PROBE_OK", kind, flush=True)
"""


def probe_kernels(
    kinds: Iterable[str],
    timeout_s: float = 180.0,
    cwd: Optional[str] = None,
) -> Dict[str, Optional[bool]]:
    """Compile-and-run Pallas kernels on tiny shapes in ONE child process.

    ``kinds`` ⊆ {"decode", "decode_windowed", "prefill",
    "prefill_windowed", "mla_decode"}. Returns per kind:
    True (compiled and ran), False (failed or timed out — do not compile
    this kernel in-process), or None (inconclusive: the child could not
    acquire the TPU because this process holds it exclusively).

    Results are memoized per process. ``DYN_SKIP_PALLAS_PROBE=1``
    short-circuits to all-True (hosts where the kernels are known-good);
    ``DYN_FORCE_XLA=1`` to all-False.
    """
    kinds = list(kinds)
    if os.environ.get("DYN_FORCE_XLA"):
        return {k: False for k in kinds}
    if os.environ.get("DYN_SKIP_PALLAS_PROBE"):
        return {k: True for k in kinds}
    todo = [k for k in kinds if k not in _PROBE_CACHE]
    if todo:
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        stdout, stderr, rc, timed_out = "", "", -1, False
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC, *todo],
                capture_output=True, text=True, timeout=timeout_s,
                cwd=cwd or repo_root, env=env,
            )
            stdout, stderr, rc = proc.stdout, proc.stderr, proc.returncode
        except subprocess.TimeoutExpired as e:
            timed_out = True
            stdout = (e.stdout or b"").decode() if isinstance(e.stdout, bytes) \
                else (e.stdout or "")
        except Exception:
            logger.exception("pallas kernel probe errored")
        exclusive = any(
            m in stderr.lower() for m in _EXCLUSIVE_DEVICE_MARKERS
        )
        for k in todo:
            if f"PROBE_OK {k}" in stdout:
                _PROBE_CACHE[k] = True
            elif exclusive:
                _PROBE_CACHE[k] = None
                logger.warning(
                    "pallas %s probe inconclusive: this process holds the "
                    "TPU exclusively; will compile in-process instead", k,
                )
            else:
                _PROBE_CACHE[k] = False
                if timed_out:
                    logger.warning(
                        "pallas %s probe timed out after %.0fs — treating "
                        "the kernel as uncompilable on this host "
                        "(XLA fallback)", k, timeout_s,
                    )
                else:
                    logger.warning(
                        "pallas %s probe failed (rc=%s): %s",
                        k, rc, stderr[-2000:],
                    )
    return {k: _PROBE_CACHE[k] for k in kinds}


def probe_kernel(
    kind: str, timeout_s: float = 180.0, cwd: Optional[str] = None
) -> bool:
    """Single-kernel probe; inconclusive counts as False (callers like
    bench.py that can simply skip the Pallas attempt)."""
    return probe_kernels([kind], timeout_s=timeout_s, cwd=cwd)[kind] is True


def probe_serving_kernels(
    mla: bool = False, softcap: bool = False, fp8_kv: bool = False,
    sinks: bool = False, verify: bool = False, sp_prefill: bool = False,
    epilogue: bool = False, timeout_s: float = 180.0,
) -> bool:
    """Probe every kernel a serving engine under ``attention_impl=auto``
    would compile — the dense engines' decode + flash-prefill kernels
    in the one specialization the model config selects, or ONLY the MLA
    decode kernel for MLA models (MLA prefill always runs the dense XLA
    formulation; models/deepseek.py). ``sp_prefill`` adds the
    sequence-parallel paged prefix-walk kernel (ops/pallas_sp.py) and
    ``epilogue`` the fused sampling tail (ops/pallas_epilogue.py) —
    both engage exactly when the engine config would compile them.

    True → let auto resolve to pallas. Any hard failure/timeout → False.
    Inconclusive (exclusive-device host) → True with a warning: a child
    can never compile there, and the in-process try/except fallback
    still guards plain failures.
    """
    if mla:
        kinds = ["mla_decode_fp8" if fp8_kv else "mla_decode"]
    else:
        # the static specialization keys are (softcap on/off, sinks
        # on/off, cache dtype) — the sliding window is a runtime operand
        # (pallas_decode: window=None rides as a 2^30 sentinel), so a
        # window-only model (Mistral/Phi-3) compiles the base pair and a
        # softcap model (Gemma-2) ONLY the softcap pair. Probing both
        # pairs for either would waste a subprocess Mosaic compile.
        sfx = "_fp8" if fp8_kv else ""
        if sinks:
            kinds = [f"decode_sinks{sfx}", f"prefill_sinks{sfx}"]
        elif softcap:
            # "windowed" probe kinds ARE the softcap specialization
            # (they compile softcap=50.0 + a window operand)
            kinds = [f"decode_windowed{sfx}", f"prefill_windowed{sfx}"]
        else:
            kinds = [f"decode{sfx}", f"prefill{sfx}"]
        if verify:
            # speculative engines also compile the S-token verify
            # kernel in the model's OWN specialization — the verify
            # kernel now carries softcap / sinks / fp8-KV variants, so
            # each config probes exactly the one it would serve with
            if sinks:
                kinds.append(f"verify_sinks{sfx}")
            elif softcap:
                kinds.append(f"verify_softcap{sfx}")
            else:
                kinds.append(f"verify{sfx}")
        if sp_prefill:
            kinds.append("sp_prefill")
    if epilogue:
        kinds.append("epilogue")
    results = probe_kernels(kinds, timeout_s=timeout_s)
    if any(v is False for v in results.values()):
        return False
    if any(v is None for v in results.values()):
        logger.warning(
            "pallas probes inconclusive (%s); proceeding with in-process "
            "compile under the warmup fallback", results,
        )
    return True
